"""KubeSchedulerConfiguration parsing, multi-profile routing, CLI."""

import json
import textwrap

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.config import types as ct
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState

REFERENCE_STYLE_YAML = """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
parallelism: 8
percentageOfNodesToScore: 50
podInitialBackoffSeconds: 2
podMaxBackoffSeconds: 20
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: NodeResourcesFit
        args:
          scoringStrategy:
            type: MostAllocated
            resources:
              - name: cpu
                weight: 2
              - name: memory
                weight: 1
      - name: InterPodAffinity
        args:
          hardPodAffinityWeight: 10
  - schedulerName: batch-scheduler
    plugins:
      score:
        enabled:
          - name: TaintToleration
            weight: 5
        disabled:
          - name: ImageLocality
extenders:
  - urlPrefix: http://127.0.0.1:10259
    filterVerb: filter
    prioritizeVerb: prioritize
    weight: 2
    nodeCacheCapable: true
    ignorable: true
tpuSolver:
  batchSize: 2048
  tieBreak: first
  meshDevices: 4
"""


def test_reference_style_yaml_parses():
    cfg = ct.load(REFERENCE_STYLE_YAML)
    assert cfg.parallelism == 8
    assert cfg.pod_initial_backoff_seconds == 2
    # percentageOfNodesToScore != 0/100 -> parsed with a warning
    assert any("percentageOfNodesToScore" in w for w in cfg.warnings)
    assert len(cfg.profiles) == 2
    p0 = cfg.profile_for("default-scheduler")
    assert p0.scoring_strategy.type == "MostAllocated"
    assert p0.hard_pod_affinity_weight == 10
    p1 = cfg.profile_for("batch-scheduler")
    assert p1.score_weights["TaintToleration"] == 5
    assert p1.score_weights["ImageLocality"] == 0
    assert cfg.extenders[0].node_cache_capable
    assert cfg.tpu_solver.batch_size == 2048
    assert cfg.tpu_solver.tie_break == "first"
    assert cfg.tpu_solver.mesh_devices == 4
    assert ct.scheduler_config(cfg).mesh_devices == 4


def test_duplicate_profile_rejected():
    import pytest

    bad = {
        "profiles": [
            {"schedulerName": "x"},
            {"schedulerName": "x"},
        ]
    }
    with pytest.raises(ValueError):
        ct.load(bad)


def test_scheduler_config_bridge():
    cfg = ct.load(REFERENCE_STYLE_YAML)
    sc = ct.scheduler_config(cfg)
    assert sc.batch_size == 2048
    # every profile becomes a routing entry
    assert set(sc.profiles) == {"default-scheduler", "batch-scheduler"}
    batch = sc.profiles["batch-scheduler"]
    assert batch.taint_weight == 5
    assert batch.image_weight == 0
    assert batch.tie_break == "first"
    assert sc.profiles["default-scheduler"].scoring_strategy == "MostAllocated"


def test_multi_profile_routing():
    cs = ClusterState()
    for i in range(4):
        cs.create_node(
            MakeNode().name(f"n{i}").capacity(
                {"cpu": "8", "memory": "32Gi", "pods": "20"}
            ).obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16,
            profiles={
                "default-scheduler": ExactSolverConfig(tie_break="first"),
                "batch-scheduler": ExactSolverConfig(tie_break="first"),
            },
        ),
    )
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    cs.create_pod(
        MakePod().name("b").scheduler_name("batch-scheduler").req({"cpu": "1"}).obj()
    )
    # a pod for an unknown scheduler is ignored entirely
    cs.create_pod(
        MakePod().name("ghost").scheduler_name("other").req({"cpu": "1"}).obj()
    )
    r = sched.schedule_batch()
    scheduled = {k for k, _ in r.scheduled}
    assert scheduled == {"default/a", "default/b"}
    assert sched.pending == 0  # ghost never queued


def test_node_update_precheck_gates_wakeups():
    cs = ClusterState()
    node = MakeNode().name("n0").capacity({"cpu": "1", "memory": "4Gi", "pods": "10"}).obj()
    cs.create_node(node)
    sched = Scheduler(cs, SchedulerConfig(batch_size=4))
    cs.create_pod(MakePod().name("big").req({"cpu": "4"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/big"]
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # irrelevant node update (no allocatable/label/taint change): stays parked
    cs.update_node(cs.get_node("n0"))
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # allocatable grows: pod moves to backoff/active
    bigger = MakeNode().name("n0").capacity({"cpu": "8", "memory": "4Gi", "pods": "10"}).obj()
    cs.update_node(bigger)
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 0
    assert counts["active"] + counts["backoff"] == 1


def test_most_allocated_strategy_parity():
    """MostAllocated (bin-packing) through solver + oracle: pods pile onto
    the already-loaded node instead of spreading."""
    from kubernetes_tpu.ops.oracle.profile import (
        FullOracle,
        ProfileWeights,
        make_oracle_nodes,
    )
    from kubernetes_tpu.tensorize.schema import (
        ResourceVocab,
        build_node_batch,
        build_pod_batch,
    )
    from kubernetes_tpu.solver.exact import ExactSolver

    nodes = [
        MakeNode().name(f"n{i}").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "20"}
        ).obj()
        for i in range(3)
    ]
    seed = MakePod().name("seed").node("n0").req({"cpu": "2", "memory": "4Gi"}).obj()
    pods = [
        MakePod().name(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj()
        for i in range(4)
    ]
    vocab = ResourceVocab.build(pods + [seed], nodes)
    nbatch = build_node_batch(nodes, {"n0": [seed]}, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    solver = ExactSolver(
        ExactSolverConfig(tie_break="first", scoring_strategy="MostAllocated")
    )
    a = solver.solve(nbatch, pbatch)
    assert all(x == 0 for x in a)  # packs onto the loaded node
    oracle = FullOracle(
        make_oracle_nodes(nodes, {"n0": [seed]}),
        ProfileWeights(scoring_strategy="MostAllocated"),
    )
    names = [nbatch.names[x] for x in a]
    errors = oracle.validate_assignments(pods, list(a), names=names)
    assert not errors, errors[:3]


def test_cli_config_command(tmp_path, capsys):
    from kubernetes_tpu.cli import main

    p = tmp_path / "cfg.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    rc = main(["--config", str(p), "config"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["profiles"][0]["scoringStrategy"] == "MostAllocated"
    assert out["tpuSolver"]["batchSize"] == 2048


def test_cli_perf_command(tmp_path, capsys):
    from kubernetes_tpu.cli import main

    wl = tmp_path / "wl.yaml"
    wl.write_text(
        textwrap.dedent(
            """
            - name: Mini
              workloadTemplate:
                - {opcode: createNodes, count: 4}
                - {opcode: createPods, count: 8, collectMetrics: true}
                - {opcode: barrier}
              workloads:
                - name: only
                  params: {}
            """
        )
    )
    rc = main(["perf", str(wl)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["scheduled"] == 8


# -- config pipeline actually honored (VERDICT r1 #7) -----------------------


def _sched_from_yaml(yaml_text, cs):
    cfg = ct.load(textwrap.dedent(yaml_text))
    return Scheduler(cs, ct.scheduler_config(cfg)), cfg


def test_disabled_filter_stops_filtering():
    """plugins.filter.disabled: [TaintToleration] — tainted nodes admit
    intolerant pods under that profile."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("tainted")
        .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .taint("dedicated", "gpu", "NoSchedule").obj()
    )
    sched, cfg = _sched_from_yaml(
        """
        apiVersion: kubescheduler.config.k8s.io/v1
        profiles:
          - schedulerName: default-scheduler
            plugins:
              filter:
                disabled:
                  - name: TaintToleration
        """,
        cs,
    )
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert ("default/p", "tainted") in r.scheduled

    # control: same cluster, default config -> unschedulable
    cs2 = ClusterState()
    cs2.create_node(
        MakeNode().name("tainted")
        .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .taint("dedicated", "gpu", "NoSchedule").obj()
    )
    sched2 = Scheduler(cs2, SchedulerConfig(batch_size=4))
    cs2.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r2 = sched2.schedule_batch()
    assert r2.unschedulable == ["default/p"]


def test_disabled_fit_filter_overcommits():
    """Disabling NodeResourcesFit admits pods beyond allocatable."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("tiny").capacity({"cpu": "1", "memory": "1Gi", "pods": "10"}).obj()
    )
    sched, _ = _sched_from_yaml(
        """
        apiVersion: kubescheduler.config.k8s.io/v1
        profiles:
          - schedulerName: default-scheduler
            plugins:
              filter:
                disabled:
                  - name: NodeResourcesFit
        """,
        cs,
    )
    cs.create_pod(MakePod().name("big").req({"cpu": "8"}).obj())
    r = sched.schedule_batch()
    assert ("default/big", "tiny") in r.scheduled


def test_rtc_scoring_changes_placement():
    """RequestedToCapacityRatio with an increasing shape prefers the MORE
    utilized node (bin-packing), the opposite of default LeastAllocated."""
    def build_cluster():
        cs = ClusterState()
        for name, used_cpu in (("empty", 0), ("busy", 6)):
            cs.create_node(
                MakeNode().name(name)
                .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"}).obj()
            )
            if used_cpu:
                cs.create_pod(
                    MakePod().name(f"filler-{name}").node(name)
                    .req({"cpu": str(used_cpu), "memory": "4Gi"}).obj()
                )
        return cs

    rtc_yaml = """
        apiVersion: kubescheduler.config.k8s.io/v1
        profiles:
          - schedulerName: default-scheduler
            plugins:
              score:
                disabled:
                  - name: NodeResourcesBalancedAllocation
            pluginConfig:
              - name: NodeResourcesFit
                args:
                  scoringStrategy:
                    type: RequestedToCapacityRatio
                    resources:
                      - name: cpu
                        weight: 1
                      - name: memory
                        weight: 1
                    requestedToCapacityRatio:
                      shape:
                        - utilization: 0
                          score: 0
                        - utilization: 100
                          score: 10
        """
    cs = build_cluster()
    sched, cfg = _sched_from_yaml(rtc_yaml, cs)
    assert not any("RequestedToCapacityRatio" in w for w in cfg.warnings)
    cs.create_pod(MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj())
    r = sched.schedule_batch()
    assert ("default/p", "busy") in r.scheduled

    # control: default LeastAllocated prefers the empty node
    cs2 = build_cluster()
    sched2 = Scheduler(
        cs2,
        SchedulerConfig(batch_size=4, solver=ExactSolverConfig(
            tie_break="first", balanced_weight=0)),
    )
    cs2.create_pod(MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj())
    r2 = sched2.schedule_batch()
    assert ("default/p", "empty") in r2.scheduled


def test_added_affinity_enforced():
    """NodeAffinityArgs.addedAffinity is a hard Filter for every pod of the
    profile (ADVICE r1: was parsed but silently unenforced)."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("blue").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .label("team", "blue").obj()
    )
    cs.create_node(
        MakeNode().name("red").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .label("team", "red").obj()
    )
    sched, _ = _sched_from_yaml(
        """
        apiVersion: kubescheduler.config.k8s.io/v1
        profiles:
          - schedulerName: default-scheduler
            pluginConfig:
              - name: NodeAffinity
                args:
                  addedAffinity:
                    requiredDuringSchedulingIgnoredDuringExecution:
                      nodeSelectorTerms:
                        - matchExpressions:
                            - key: team
                              operator: In
                              values: ["blue"]
        """,
        cs,
    )
    for i in range(4):
        cs.create_pod(MakePod().name(f"p-{i}").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 4
    assert all(node == "blue" for _, node in r.scheduled)


def test_fit_resource_weights_change_scoring():
    """scoringStrategy.resources weights shift LeastAllocated preferences:
    with cpu weight dominant, the cpu-idle node wins even though it is
    memory-loaded."""
    def build_cluster():
        cs = ClusterState()
        cs.create_node(
            MakeNode().name("cpu-idle")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"}).obj()
        )
        cs.create_node(
            MakeNode().name("mem-idle")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"}).obj()
        )
        # cpu-idle: memory mostly used; mem-idle: cpu mostly used
        cs.create_pod(
            MakePod().name("mem-hog").node("cpu-idle").req({"memory": "12Gi"}).obj()
        )
        cs.create_pod(
            MakePod().name("cpu-hog").node("mem-idle").req({"cpu": "6"}).obj()
        )
        return cs

    yaml_w = """
        apiVersion: kubescheduler.config.k8s.io/v1
        profiles:
          - schedulerName: default-scheduler
            plugins:
              score:
                disabled:
                  - name: NodeResourcesBalancedAllocation
            pluginConfig:
              - name: NodeResourcesFit
                args:
                  scoringStrategy:
                    type: LeastAllocated
                    resources:
                      - name: cpu
                        weight: 9
                      - name: memory
                        weight: 1
        """
    cs = build_cluster()
    sched, _ = _sched_from_yaml(yaml_w, cs)
    cs.create_pod(MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj())
    r = sched.schedule_batch()
    assert ("default/p", "cpu-idle") in r.scheduled


def test_unsupported_scoring_resource_warns():
    cfg = ct.load(
        textwrap.dedent(
            """
            apiVersion: kubescheduler.config.k8s.io/v1
            profiles:
              - schedulerName: default-scheduler
                pluginConfig:
                  - name: NodeResourcesFit
                    args:
                      scoringStrategy:
                        type: LeastAllocated
                        resources:
                          - name: nvidia.com/gpu
                            weight: 3
            """
        )
    )
    ct.scheduler_config(cfg)
    assert any("nvidia.com/gpu" in w for w in cfg.warnings)


def test_rtc_shape_malformed_entry_warns_and_falls_back():
    """A shape point missing utilization/score degrades to LeastAllocated
    with a warning instead of raising KeyError at config load (ADVICE r2)."""
    cfg = ct.load(
        textwrap.dedent(
            """
            apiVersion: kubescheduler.config.k8s.io/v1
            profiles:
              - schedulerName: default-scheduler
                pluginConfig:
                  - name: NodeResourcesFit
                    args:
                      scoringStrategy:
                        type: RequestedToCapacityRatio
                        requestedToCapacityRatio:
                          shape:
                            - utilization: 0
                            - score: 10
            """
        )
    )
    scfg = ct.scheduler_config(cfg)
    assert any("malformed" in w for w in cfg.warnings)
    assert scfg.solver.rtc_shape == ()
    # the solver's scorer dispatch with no shape is LeastAllocated
    assert scfg.solver.scoring_strategy == "RequestedToCapacityRatio"


def test_rtc_shape_non_ascending_warns_and_falls_back():
    """Non-ascending utilization breakpoints break the piecewise
    interpolation's assumptions; validation warns + falls back (ADVICE r2)."""
    cfg = ct.load(
        textwrap.dedent(
            """
            apiVersion: kubescheduler.config.k8s.io/v1
            profiles:
              - schedulerName: default-scheduler
                pluginConfig:
                  - name: NodeResourcesFit
                    args:
                      scoringStrategy:
                        type: RequestedToCapacityRatio
                        requestedToCapacityRatio:
                          shape:
                            - utilization: 50
                              score: 5
                            - utilization: 50
                              score: 10
            """
        )
    )
    scfg = ct.scheduler_config(cfg)
    assert any("ascending" in w for w in cfg.warnings)
    assert scfg.solver.rtc_shape == ()


def test_score_disable_independent_of_filter_disable():
    """plugins.score.disabled and plugins.filter.disabled are separate lists
    (runtime/framework.go builds per-extension-point pipelines): disabling
    InterPodAffinity's Filter keeps its Score weight, and vice versa."""
    cfg = ct.load(
        textwrap.dedent(
            """
            apiVersion: kubescheduler.config.k8s.io/v1
            profiles:
              - schedulerName: default-scheduler
                plugins:
                  filter:
                    disabled:
                      - name: InterPodAffinity
                  score:
                    disabled:
                      - name: TaintToleration
            """
        )
    )
    scfg = ct.scheduler_config(cfg)
    assert "InterPodAffinity" in scfg.solver.disabled_filters
    assert scfg.solver.interpod_weight == 2  # score stage still enabled
    assert scfg.solver.taint_weight == 0  # score disabled
    assert "TaintToleration" not in scfg.solver.disabled_filters


def test_fleet_section_round_trip(tmp_path, capsys):
    """fleet.hubAddress / fleet.meshSlice (ISSUE 11): parse -> typed
    section -> runtime SchedulerConfig -> cli config dump, with the
    null-tolerant convention (explicit YAML nulls default instead of
    TypeError-ing) and hard validation for the dangerous typos."""
    import pytest

    yaml_doc = textwrap.dedent(
        """
        fleet:
          replica: r2
          replicas: [r0, r1, r2, r3]
          hubAddress: "hub.scheduling.svc:9411"
          meshSlice: "2/4"
          maxRowAgeSeconds: 15
        """
    )
    cfg = ct.load(yaml_doc)
    assert cfg.fleet.replica == "r2"
    assert cfg.fleet.replicas == ["r0", "r1", "r2", "r3"]
    assert cfg.fleet.hub_address == "hub.scheduling.svc:9411"
    assert cfg.fleet.mesh_slice == (2, 4)
    assert cfg.fleet.max_row_age_seconds == 15.0
    scfg = ct.scheduler_config(cfg)
    assert scfg.mesh_slice == (2, 4)
    assert scfg.fleet.replica == "r2"
    assert scfg.fleet.replicas == ("r0", "r1", "r2", "r3")
    assert scfg.fleet.hub_address == "hub.scheduling.svc:9411"
    assert scfg.fleet.max_row_age_s == 15.0
    # the cli dump round-trips the section (meshSlice back in its
    # "rank/count" wire shape)
    from kubernetes_tpu.cli import main

    p = tmp_path / "fleet.yaml"
    p.write_text(yaml_doc)
    assert main(["--config", str(p), "config"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fleet"] == {
        "replica": "r2",
        "replicas": ["r0", "r1", "r2", "r3"],
        "hubAddress": "hub.scheduling.svc:9411",
        "meshSlice": "2/4",
        "maxRowAgeSeconds": 15.0,
        "flushBatch": 0,
    }
    # null-tolerant: explicit nulls default, fleet stays off
    cfg2 = ct.load(
        "fleet:\n  replica: null\n  meshSlice: null\n  hubAddress: null\n"
    )
    assert cfg2.fleet.replica == "" and cfg2.fleet.mesh_slice is None
    assert ct.scheduler_config(cfg2).fleet is None
    # validation: the typos that would silently share devices or
    # misroute the hub are hard errors
    for bad in (
        'fleet:\n  replica: r0\n  meshSlice: "4/4"',
        'fleet:\n  replica: r0\n  meshSlice: "-1/4"',
        'fleet:\n  replica: r0\n  meshSlice: "x"',
        'fleet:\n  replica: r0\n  hubAddress: "no-port"',
        'fleet:\n  replica: r0\n  maxRowAgeSeconds: 0',
        "fleet:\n  replicas: [a, b]",
        # meshSlice with fleet mode off would silently pin the sole
        # scheduler to a fraction of the devices (review-caught)
        'fleet:\n  meshSlice: "0/4"',
    ):
        with pytest.raises(ValueError):
            ct.load(bad)
