"""Long sim soaks — every profile, multiple seeds, deeper cycle counts.

Marked ``slow``: tier-1 deselects these (-m 'not slow'); run them
explicitly before touching the scheduling loop's concurrency story:

    JAX_PLATFORMS=cpu python -m pytest tests/test_sim_soak.py -m slow
"""

import pytest

from kubernetes_tpu.sim import PROFILES, run_sim

pytestmark = pytest.mark.slow


@pytest.mark.parametrize("profile", sorted(PROFILES))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_profile(profile, seed):
    res = run_sim(profile, seed=seed, cycles=25)
    assert res.violations == [], [v.as_dict() for v in res.violations]
    assert res.settled


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_soak_churn_heavy_deep_deterministic(seed):
    a = run_sim("churn_heavy", seed=seed, cycles=40)
    b = run_sim("churn_heavy", seed=seed, cycles=40)
    assert a.trace.digest() == b.trace.digest()
    assert a.bindings == b.bindings
    assert a.violations == [] and a.settled


@pytest.mark.parametrize("seed", [5, 6])
def test_soak_backlog_drain_mega(seed):
    """The backlog_drain profile at soak scale (ISSUE 12): a 400-pod
    seeded mega-backlog (sim-relative) with the hard-shape mix drained
    through drain_backlog's budget-planned chunked streaming path,
    byte-deterministic across runs, budget auto-split engaged, zero
    invariant violations."""
    import dataclasses

    from kubernetes_tpu.sim import get_profile

    prof = dataclasses.replace(
        get_profile("backlog_drain"),
        backlog=400,
        nodes=24,
        node_cpu="32",
    )
    a = run_sim(prof, seed=seed, cycles=12)
    b = run_sim(prof, seed=seed, cycles=12)
    assert a.violations == [], [v.as_dict() for v in a.violations]
    assert a.settled
    assert a.summary["backlog"]["budget_splits"] >= 1
    assert a.summary["backlog"]["chunks"] >= 10
    assert a.trace.digest() == b.trace.digest()


def test_soak_sync_vs_pipelined_agree_on_quiet_cluster():
    """With no faults or churn racing mid-flight (node_flaps is prompt
    delivery), the pipelined and synchronous drivers must settle every
    pod — cross-driver sanity over a long run."""
    a = run_sim("node_flaps", seed=9, cycles=30, pipelined=True)
    b = run_sim("node_flaps", seed=9, cycles=30, pipelined=False)
    assert a.violations == [] and b.violations == []
    assert a.settled and b.settled
    # identical churn stream (same seed) => identical pod population
    assert set(a.bindings) | set(a.unbound) == set(b.bindings) | set(
        b.unbound
    )
