"""In-memory cluster-state service — the [BOUNDARY] stand-in for
apiserver + etcd (SURVEY.md §8.3).

What it emulates (and what the scheduler actually exercises of the real
thing):
- typed Pod/Node storage with a single monotonically-increasing
  resourceVersion stream (etcd revision equivalent);
- optimistic concurrency: updates carrying a stale resourceVersion are
  rejected with Conflict, like apiserver's 409s;
- watch streams: subscribers receive ADDED/MODIFIED/DELETED events in
  commit order, like client-go Reflector/informers (delivery is synchronous
  in-process — the informer layer of SURVEY §3.3 collapses to an event bus);
- the **pods/{name}/binding subresource**
  (pkg/registry/core/pod/storage/storage.go#BindingREST.Create): atomically
  sets spec.nodeName on a still-unbound pod; rejects if the pod is gone,
  already bound, or the target node doesn't exist — the reject paths the
  scheduler's assume/forget protocol must survive;
- fault injection hooks (bind_fault) so tests can simulate conflicts and
  node disappearance mid-cycle (SURVEY §6.3).
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterable, Literal

from ..api.objects import (
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
)

EventType = Literal["ADDED", "MODIFIED", "DELETED"]


class ApiError(Exception):
    def __init__(self, reason: str, message: str = ""):
        self.reason = reason  # Conflict | NotFound | AlreadyExists | Invalid
        super().__init__(f"{reason}: {message}")


@dataclass
class Event:
    type: EventType
    kind: str  # "Pod" | "Node"
    obj: Pod | Node
    resource_version: int


Watcher = Callable[[Event], None]


class ClusterState:
    """In-memory store guarded by one RLock (``self.lock``), the analog of
    the reference's mutex-guarded cache (SURVEY §6.2). The serve path
    mutates it from three threads (aiohttp event loop ingest, the scheduler
    drain executor, gRPC workers); every public method takes the lock, and
    watch callbacks fire under it so subscriber state (queue/cache) updates
    are serialized with the writes that caused them. The Scheduler holds
    the same lock across a whole schedule_batch, which makes its
    pop -> solve -> bind cycle atomic with respect to ingest."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self._rv = 0
        self._pods: dict[str, Pod] = {}  # key = ns/name
        self._nodes: dict[str, Node] = {}
        self._pdbs: dict[str, PodDisruptionBudget] = {}
        self._pvs: dict[str, PersistentVolume] = {}
        self._pvcs: dict[str, PersistentVolumeClaim] = {}
        self._services: dict[str, object] = {}
        self._watchers: list[Watcher] = []
        # fault injection: called with (pod, node_name) before a bind commits;
        # raise ApiError to simulate apiserver-side rejection
        self.bind_fault: Callable[[Pod, str], None] | None = None

    # -- watch plumbing --

    def subscribe(self, w: Watcher) -> None:
        self._watchers.append(w)

    def _emit(self, etype: EventType, kind: str, obj: Pod | Node) -> None:
        ev = Event(etype, kind, obj, self._rv)
        for w in list(self._watchers):
            w(ev)

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    @property
    def resource_version(self) -> int:
        return self._rv

    # -- pods --

    def create_pod(self, pod: Pod) -> Pod:
        if pod.key in self._pods:
            raise ApiError("AlreadyExists", pod.key)
        pod.resource_version = self._next_rv()
        self._pods[pod.key] = pod
        self._emit("ADDED", "Pod", pod)
        return pod

    def get_pod(self, namespace: str, name: str) -> Pod:
        key = f"{namespace}/{name}"
        try:
            return self._pods[key]
        except KeyError:
            raise ApiError("NotFound", key) from None

    def update_pod(self, pod: Pod, expect_rv: int | None = None) -> Pod:
        cur = self.get_pod(pod.namespace, pod.name)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError("Conflict", f"{pod.key} rv {cur.resource_version} != {expect_rv}")
        pod.resource_version = self._next_rv()
        self._pods[pod.key] = pod
        self._emit("MODIFIED", "Pod", pod)
        return pod

    def patch_pod_status(
        self, namespace: str, name: str, *, nominated_node_name: str | None = None,
        phase: str | None = None
    ) -> Pod:
        pod = self.get_pod(namespace, name)
        if nominated_node_name is not None:
            pod.nominated_node_name = nominated_node_name
        if phase is not None:
            pod.phase = phase
        pod.resource_version = self._next_rv()
        self._emit("MODIFIED", "Pod", pod)
        return pod

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        pod = self._pods.pop(key, None)
        if pod is None:
            raise ApiError("NotFound", key)
        self._next_rv()
        self._emit("DELETED", "Pod", pod)

    def list_pods(self) -> list[Pod]:
        return list(self._pods.values())

    def bind(self, namespace: str, name: str, node_name: str) -> None:
        """POST pods/{name}/binding — the commit point of a scheduling cycle."""
        pod = self.get_pod(namespace, name)
        if pod.node_name:
            raise ApiError("Conflict", f"{pod.key} already bound to {pod.node_name}")
        if node_name not in self._nodes:
            raise ApiError("NotFound", f"node {node_name}")
        if self.bind_fault is not None:
            self.bind_fault(pod, node_name)
        pod.node_name = node_name
        pod.resource_version = self._next_rv()
        self._emit("MODIFIED", "Pod", pod)

    # -- nodes --

    def create_node(self, node: Node) -> Node:
        if node.name in self._nodes:
            raise ApiError("AlreadyExists", node.name)
        node.resource_version = self._next_rv()
        self._nodes[node.name] = node
        self._emit("ADDED", "Node", node)
        return node

    def get_node(self, name: str) -> Node:
        try:
            return self._nodes[name]
        except KeyError:
            raise ApiError("NotFound", name) from None

    def update_node(self, node: Node, expect_rv: int | None = None) -> Node:
        cur = self.get_node(node.name)
        if expect_rv is not None and cur.resource_version != expect_rv:
            raise ApiError("Conflict", f"{node.name} rv {cur.resource_version} != {expect_rv}")
        node.resource_version = self._next_rv()
        self._nodes[node.name] = node
        self._emit("MODIFIED", "Node", node)
        return node

    def delete_node(self, name: str) -> None:
        node = self._nodes.pop(name, None)
        if node is None:
            raise ApiError("NotFound", name)
        self._next_rv()
        self._emit("DELETED", "Node", node)

    def list_nodes(self) -> list[Node]:
        return list(self._nodes.values())

    # -- PodDisruptionBudgets (policy/v1 slice preemption reads) --

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        if pdb.key in self._pdbs:
            raise ApiError("AlreadyExists", pdb.key)
        pdb.resource_version = self._next_rv()
        self._pdbs[pdb.key] = pdb
        return pdb

    def delete_pdb(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        if self._pdbs.pop(key, None) is None:
            raise ApiError("NotFound", key)
        self._next_rv()

    def list_pdbs(self) -> list[PodDisruptionBudget]:
        return list(self._pdbs.values())

    # -- Services (PodTopologySpread System-defaulting input) --

    def create_service(self, svc) -> object:
        if svc.key in self._services:
            raise ApiError("AlreadyExists", svc.key)
        svc.resource_version = self._next_rv()
        self._services[svc.key] = svc
        return svc

    def delete_service(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        if self._services.pop(key, None) is None:
            raise ApiError("NotFound", key)
        self._next_rv()

    def list_services(self) -> list:
        return list(self._services.values())

    # -- PersistentVolumes / Claims (volume plugin inputs) --

    def create_pv(self, pv: PersistentVolume) -> PersistentVolume:
        if pv.name in self._pvs:
            raise ApiError("AlreadyExists", pv.name)
        pv.resource_version = self._next_rv()
        self._pvs[pv.name] = pv
        return pv

    def list_pvs(self) -> list[PersistentVolume]:
        return list(self._pvs.values())

    def update_pv(self, pv: PersistentVolume) -> PersistentVolume:
        if pv.name not in self._pvs:
            raise ApiError("NotFound", pv.name)
        pv.resource_version = self._next_rv()
        self._pvs[pv.name] = pv
        return pv

    def create_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        if pvc.key in self._pvcs:
            raise ApiError("AlreadyExists", pvc.key)
        pvc.resource_version = self._next_rv()
        self._pvcs[pvc.key] = pvc
        return pvc

    def list_pvcs(self) -> list[PersistentVolumeClaim]:
        return list(self._pvcs.values())

    def update_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        if pvc.key not in self._pvcs:
            raise ApiError("NotFound", pvc.key)
        pvc.resource_version = self._next_rv()
        self._pvcs[pvc.key] = pvc
        return pvc

    # -- bulk helpers for benchmarks --

    def create_nodes(self, nodes: Iterable[Node]) -> None:
        for n in nodes:
            self.create_node(n)

    def create_pods(self, pods: Iterable[Pod]) -> None:
        for p in pods:
            self.create_pod(p)


def _locked(fn):
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self.lock:
            return fn(self, *args, **kwargs)

    return wrapper


# Guard every public method with the instance RLock (reentrant: e.g. the
# scheduler's preemption path calls delete_pod while holding the lock
# across schedule_batch).
for _name, _fn in list(vars(ClusterState).items()):
    if _name.startswith("_") or not callable(_fn):
        continue
    setattr(ClusterState, _name, _locked(_fn))
del _name, _fn
