"""Suppression-debt ratchet.

Every ``# ktpu: ignore[RULE]: reason`` is technical debt: code the
rules believe is wrong, waved through by hand. The ratchet pins
today's debt in a committed baseline
(``analysis/suppression_baseline.json``) and CI fails when the count
GROWS — per rule, not just in total, so trading a TPU001 ignore for a
new FENCE001 ignore is visible. Shrinking is always allowed (and the
next ``--write-baseline`` commits the better number).

The unit counted is the ignore DIRECTIVE per rule it names (one
``ignore[TPU001,LOCK001]`` line counts once for each rule), not the
findings it happens to match — so a directive that stops matching
anything still shows up as debt until it is deleted, which is exactly
the nudge we want.
"""

from __future__ import annotations

import json
from pathlib import Path

BASELINE_PATH = Path(__file__).resolve().parent / "suppression_baseline.json"


def count_suppressions(modules) -> dict:
    """{"total": n, "rules": {rule: n}} over the analyzed modules."""
    rules: dict[str, int] = {}
    total = 0
    for m in modules:
        for s in m.suppressions:
            total += 1
            for r in s.rules:
                rules[r] = rules.get(r, 0) + 1
    return {"total": total, "rules": dict(sorted(rules.items()))}


def render_baseline(counts: dict) -> str:
    return json.dumps(counts, indent=2, sort_keys=True) + "\n"


def load_baseline(path: Path | None = None) -> dict | None:
    p = path or BASELINE_PATH
    if not p.exists():
        return None
    return json.loads(p.read_text())


def check_ratchet(counts: dict, baseline: dict | None) -> list[str]:
    """Human-readable violations; empty means the ratchet holds."""
    if baseline is None:
        return [
            "no committed suppression baseline "
            f"({BASELINE_PATH.name}) — write one: "
            "python -m kubernetes_tpu.analysis --write-baseline"
        ]
    out = []
    if counts["total"] > baseline.get("total", 0):
        out.append(
            f"suppression count grew: {counts['total']} > baseline "
            f"{baseline.get('total', 0)}"
        )
    base_rules = baseline.get("rules", {})
    for rule, n in sorted(counts["rules"].items()):
        if n > base_rules.get(rule, 0):
            out.append(
                f"suppressions for {rule} grew: {n} > baseline "
                f"{base_rules.get(rule, 0)}"
            )
    if out:
        out.append(
            "fix the finding instead of suppressing it; if the "
            "suppression is genuinely correct, bump the baseline in "
            "the same commit: python -m kubernetes_tpu.analysis "
            "--write-baseline"
        )
    return out
