"""Shipped analyzer configuration: the audited whitelists and path
scopes for the kubernetes_tpu package.

SANCTIONED_SYNC_POINTS is the contract at the heart of the pipelined
solver (BENCH_r05: ~104 ms per host<->device sync post-first-read): the
hot path may read device values through EXACTLY these three points —

- ``DeferredAssignments.get`` (solver/exact.py): the deferred
  assignment download whose async D2H copy was started at dispatch, so
  the blocking read lands after the tunnel RTT has been overlapped.
- ``DeferredAssignments.wait`` (solver/exact.py): the streaming
  dispatcher's completion thread parks here so the tunnel RTT is paid
  OFF the driver thread — it only waits for the async D2H started at
  dispatch to land and never converts the value; the driver's ``get``
  stays the one read.
- ``_InFlightSolve.assignments`` (scheduler.py): the scheduler-side
  wrapper the apply path calls once per batch.

Adding an entry is a design decision, not a lint tweak: it must come
with the same overlap analysis these carry.
"""

from __future__ import annotations

from .core import AnalysisContext

SANCTIONED_SYNC_POINTS = frozenset(
    {
        ("kubernetes_tpu/solver/exact.py", "DeferredAssignments.get"),
        ("kubernetes_tpu/solver/exact.py", "DeferredAssignments.wait"),
        ("kubernetes_tpu/scheduler.py", "_InFlightSolve.assignments"),
    }
)

# TPU003 dtype discipline applies where tensors feed the solve pipeline
# (a weakly-typed float literal silently re-specializes the jit cache).
# The solver/ prefix covers every engine — exact, single_shot, and the
# convex-relaxation mega-planner (solver/relax.py, ISSUE 19) — so a new
# kernel file inherits the discipline without a registry edit.
DTYPE_PATHS = (
    "kubernetes_tpu/ops/",
    "kubernetes_tpu/solver/",
)

# MET001 scans these for metric usage against metrics/__init__.py.
METRIC_SCAN_PATHS = (
    "kubernetes_tpu/scheduler.py",
    "kubernetes_tpu/resilience.py",
    "kubernetes_tpu/server/",
    "kubernetes_tpu/solver/",
    "kubernetes_tpu/sim/",
    "kubernetes_tpu/obs/",
    "kubernetes_tpu/fleet/",
    "kubernetes_tpu/rebalance/",
    "kubernetes_tpu/tuning/",
)


def default_context() -> AnalysisContext:
    return AnalysisContext(
        sanctioned_sync=SANCTIONED_SYNC_POINTS,
        dtype_paths=DTYPE_PATHS,
        metric_scan_paths=METRIC_SCAN_PATHS,
        metric_attrs=None,  # resolved lazily from kubernetes_tpu/metrics
    )
