"""Multi-chip sharding correctness on the 8-device virtual CPU mesh
(SURVEY §6.7; conftest.py provisions the devices).

The node axis is this framework's "sequence/context" dimension: node tables
and carried state shard over it, per-pod inputs replicate, and XLA/GSPMD
inserts the collectives (argmax, cumsum, segment reductions become
cross-shard). These tests prove sharded == unsharded BIT-EQUALITY for both
solvers — the property the driver's dryrun_multichip compile-checks but
cannot assert against a single-chip reference."""

import functools

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from __graft_entry__ import _STATIC_KW, _example_args
from kubernetes_tpu.solver.exact import _solve_scan
from kubernetes_tpu.solver.single_shot import SingleShotConfig, SingleShotSolver
from kubernetes_tpu.tensorize.schema import build_node_batch, build_pod_batch
from kubernetes_tpu.api.wrappers import MakeNode, MakePod

N_DEVICES = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEVICES:
        pytest.skip(f"needs {N_DEVICES} virtual devices")
    return Mesh(np.array(jax.devices()[:N_DEVICES]), axis_names=("nodes",))


def _shardings(mesh, tables, state0, xs):
    shard_2d = NamedSharding(mesh, P(None, "nodes"))
    shard_1d = NamedSharding(mesh, P("nodes"))
    repl = NamedSharding(mesh, P())

    def node_sharding(a):
        if a.ndim == 2:
            return shard_2d
        return shard_1d

    tables_sh = jtu.tree_map(node_sharding, tables)
    # per-instance/per-class scalar tables are replicated (no node axis)
    for grp, names in (
        ("spr", ("max_skew", "min_domains", "self_match", "is_hostname", "hard", "soft")),
        ("ipa", ("in_pref_w", "cls_req_aff", "cls_req_anti", "cls_pref", "ex_anti")),
    ):
        for name in names:
            tables_sh[grp][name] = repl
    state_sh = jtu.tree_map(node_sharding, state0)
    xs_sh = jtu.tree_map(lambda a: repl, xs)
    return tables_sh, state_sh, xs_sh, repl


def test_exact_scan_sharded_equals_unsharded(mesh):
    """The full exact-parity scan (spread + interpod active) over a 1024-node
    axis sharded 8 ways must produce the identical assignment sequence and
    final node state."""
    tables, state0, xs = _example_args(n_nodes=1024, n_pods=64)
    fn = functools.partial(_solve_scan, **_STATIC_KW, fdtype=jnp.float32)
    key = jax.random.PRNGKey(0)

    ref_asg, ref_state = jax.jit(fn)(tables, state0, xs, key)
    ref_asg = np.asarray(ref_asg)

    tables_sh, state_sh, xs_sh, repl = _shardings(mesh, tables, state0, xs)
    out = jax.jit(fn, in_shardings=(tables_sh, state_sh, xs_sh, repl))(
        jtu.tree_map(jax.device_put, tables, tables_sh),
        jtu.tree_map(jax.device_put, state0, state_sh),
        jtu.tree_map(jax.device_put, xs, xs_sh),
        jax.device_put(key, repl),
    )
    np.testing.assert_array_equal(np.asarray(out[0]), ref_asg)
    for k in ref_state:
        np.testing.assert_array_equal(
            np.asarray(out[1][k]), np.asarray(ref_state[k]), err_msg=k
        )
    assert int((ref_asg >= 0).sum()) == 64  # everything placed


def _single_shot_workload(n_nodes=1024, n_pods=768):
    rng = np.random.default_rng(42)
    nodes = [
        MakeNode()
        .name(f"n-{i:04}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "40"})
        .obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        cpu = int(rng.integers(1, 8)) * 250
        mem = int(rng.integers(1, 5)) << 29
        pods.append(
            MakePod()
            .name(f"p-{i:04}")
            .req({"cpu": f"{cpu}m", "memory": mem})
            .priority(int(rng.integers(0, 5)))
            .obj()
        )
    batch = build_node_batch(nodes)
    pbatch = build_pod_batch(pods, batch.vocab)
    return batch, pbatch


def test_parallel_sharding_helpers(mesh):
    """parallel/sharding.py: the mesh/spec helpers used by the solvers."""
    from kubernetes_tpu.parallel.sharding import (
        device_put_tree,
        node_mesh,
        node_sharding,
        replicated,
        shard_node_tree,
    )

    m = node_mesh(N_DEVICES)
    assert m.axis_names == ("nodes",)
    s2 = node_sharding(m, 2)
    assert s2.spec == (None, "nodes")
    s1 = node_sharding(m, 1)
    assert s1.spec == ("nodes",)
    assert replicated(m).spec == ()

    tree = {
        "alloc": np.zeros((3, 1024), np.int64),
        "max_skew": np.ones(8, np.int32),
    }
    sh = shard_node_tree(m, tree, replicate_names=frozenset({"max_skew"}))
    assert sh["alloc"].spec == (None, "nodes")
    assert sh["max_skew"].spec == ()
    placed = device_put_tree(tree, sh)
    np.testing.assert_array_equal(np.asarray(placed["alloc"]), tree["alloc"])


def test_single_shot_sharded_equals_unsharded(mesh):
    """The auction solver — the 50k x 10k rebalance engine, i.e. the actual
    v5e-8 workload — sharded over the node axis must commit the identical
    assignment vector and node state."""
    batch_ref, pbatch = _single_shot_workload()
    batch_sh, _ = _single_shot_workload()

    solver = SingleShotSolver(SingleShotConfig())
    ref = solver.solve(batch_ref, pbatch)
    sharded = solver.solve(batch_sh, pbatch, mesh=mesh)

    np.testing.assert_array_equal(sharded, ref)
    np.testing.assert_array_equal(batch_sh.used, batch_ref.used)
    np.testing.assert_array_equal(batch_sh.pod_count, batch_ref.pod_count)
    placed = int((ref >= 0).sum())
    assert placed == pbatch.num_pods  # capacity is ample: all place
