"""TPU004 — cross-module host-sync escape analysis.

TPU001's scope is intra-module by design: a ``# ktpu: hot`` function
calling ``helper()`` in the SAME file propagates hotness, but a call
into another module does not — so a hot apply-path function calling a
cross-module helper that blocks on the device was invisible. TPU004
re-runs the scope BFS over the PROJECT call graph (imports, methods on
typed attributes, constructors — see :mod:`..project`) and flags the
*definite* sync primitives in the expanded scope:

- ``.item()`` — flagged in BOTH the cross-module extension and the
  intra-module scope (TPU001 predates it; scalar ``.item()`` reads are
  the classic accidental sync);
- ``.tolist()`` / ``.block_until_ready()`` — flagged only in functions
  the PROJECT graph adds (functions already in their module's own
  scope are TPU001's findings; reporting them twice would double every
  fix).

``np.asarray``-style transfers are deliberately NOT extended across
modules: the cross-module closure reaches large stretches of
host-resident bookkeeping where numpy-on-host is legitimate, and the
false-positive flood would drown the rule. Explicit device reads have
no such ambiguity.

Cold marks and the sanctioned sync whitelist barrier the BFS exactly
as in TPU001. Findings carry the root chain (``hot root A -> B -> C``)
so the report explains WHY a function is in scope.
"""

from __future__ import annotations

import ast

from ..callgraph import own_nodes
from ..core import AnalysisContext, Finding
from ..project import ProjectGraph, ProjectPass

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# flagged even when TPU001 already covers the function (it does not
# know .item())
_ITEM_ONLY = {"item"}


class CrossModuleSyncPass(ProjectPass):
    rule = "TPU004"
    title = "cross-module host-sync escape analysis"

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        traced, hot, via = project.global_scopes()
        findings: list[Finding] = []
        for node_id in sorted(traced | hot):
            rel, qual = node_id
            finfo = project.function(node_id)
            m = project.modules.get(rel)
            if finfo is None or m is None:
                continue
            intra_traced, intra_hot = project.intra_scopes(rel)
            in_intra = qual in intra_traced or qual in intra_hot
            flag = _ITEM_ONLY if in_intra else _SYNC_METHODS
            for node in own_nodes(finfo.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in flag
                ):
                    continue
                chain = project.root_chain(node_id, via)
                route = " -> ".join(q for (_r, q) in chain)
                kind = "hot" if node_id in hot else "traced"
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=m.path,
                        line=node.lineno,
                        message=(
                            f".{node.func.attr}() forces a host sync in "
                            f"'{qual}', reached from a {kind} root via "
                            f"{route}"
                        ),
                        hint=(
                            "move the read behind the sanctioned "
                            "deferred-read boundary, mark the function "
                            "'# ktpu: cold' if it is off the hot path, "
                            "or batch the scalar out with the deferred "
                            "assignments"
                        ),
                    )
                )
        return findings
