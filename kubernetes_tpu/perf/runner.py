"""scheduler_perf-compatible YAML workload runner (SURVEY.md §4.5, §8.6).

Parses the same testCase/workload shape as
test/integration/scheduler_perf/config/performance-config.yaml:

    - name: SchedulingBasic
      workloadTemplate:
        - opcode: createNodes
          countParam: $initNodes
          nodeTemplatePath: config/node-default.yaml   # or nodeTemplate: {}
        - opcode: createPods
          countParam: $initPods
        - opcode: barrier
        - opcode: createPods
          countParam: $measurePods
          collectMetrics: true
        - opcode: barrier
      workloads:
        - name: 500Nodes
          params: {initNodes: 500, initPods: 500, measurePods: 1000}

Supported opcodes: createNodes, createPods, createNamespaces, barrier,
sleep, churn (create/delete pods at a rate between scheduling batches),
createPodsSteady (open-loop: pods arrive at a fixed rate while the
scheduler drains concurrently — the arrival-driven sustained workload).
Templates load from nodeTemplatePath/podTemplatePath (YAML manifests parsed
through the same wire decoders the extender uses) or inline
nodeTemplate/podTemplate maps; absent both, a default 32-core node /
1-core pod is used. $param indirection and {{.Index}}-style name suffixes
are handled ({{.Index}} is replaced; other template actions are not).

Measurement mirrors scheduler_perf's SchedulingThroughput collector:
pods/s sampled per scheduling batch over the collectMetrics phases, with
avg/p50/p90/p99 summary, per-pod e2e (queue-entry -> bind) latency
percentiles, and the per-batch device-solve seconds. A workload-level
``threshold`` (pods/s, the upstream scheduler_perf field) FAILS the
workload when measured POST-WARMUP steady-state throughput lands below
it — the perf CLI exits nonzero, so perf regressions gate like test
failures (scheduler_perf.go's threshold assert [U]; VERDICT r4 #3).
Steady-state means the first measured batch (which usually carries the
XLA compile stall) is excluded, time-weighted over the remaining
batches: gating the avg let one slow compile dominate the whole run and
made the floor either flaky or toothless (r6 satellite — the
SteadyStateArrival floor now actually protects sustained capability).

Scheduling drains through Scheduler.run_pipelined (double-buffered device
solves) by default; pass pipelined=False for the synchronous loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

import numpy as np
import yaml

from ..api.objects import Node, Pod
from ..scheduler import Scheduler, SchedulerConfig
from ..state.cluster import ClusterState

DEFAULT_NODE = {
    "metadata": {"name": "node-{{.Index}}"},
    "status": {
        "allocatable": {"cpu": "32", "memory": "128Gi", "pods": "110"},
        "capacity": {"cpu": "32", "memory": "128Gi", "pods": "110"},
    },
}
DEFAULT_POD = {
    "metadata": {"name": "pod-{{.Index}}"},
    "spec": {
        "containers": [
            {
                "name": "c",
                "image": "registry.k8s.io/pause:3.9",
                "resources": {"requests": {"cpu": "1", "memory": "500Mi"}},
            }
        ]
    },
}


@dataclass
class WorkloadResult:
    test_case: str
    workload: str
    scheduled: int = 0
    unschedulable: int = 0
    measured_pods: int = 0
    measure_seconds: float = 0.0
    solve_seconds: float = 0.0
    samples: list[float] = field(default_factory=list)  # pods/s per batch
    # per measured batch: (wall seconds, pods bound) — the time-weighted
    # inputs behind the steady-state number (a rate mean over batches
    # would over-weight tiny batches)
    batch_samples: list[tuple[float, int]] = field(default_factory=list)
    # per-pod e2e latency (first queue entry -> bind), measured phases only
    pod_latencies: list[float] = field(default_factory=list)
    threshold: float = 0.0  # pods/s floor (scheduler_perf threshold assert)
    passed: bool = True

    def steady_pods_per_sec(self) -> float:
        """Post-warmup steady-state throughput: pods/s time-weighted
        over the measured batches EXCLUDING the first (which usually
        carries the XLA compile stall). Falls back to the overall avg
        when only one batch was measured."""
        tail = self.batch_samples[1:]
        dt = sum(t for t, _ in tail)
        if dt > 0:
            return sum(n for _, n in tail) / dt
        if self.measure_seconds:
            return self.measured_pods / self.measure_seconds
        return 0.0

    def throughput_summary(self) -> dict[str, float]:
        if not self.samples:
            return {"avg": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0, "steady": 0.0}
        a = np.asarray(self.samples)
        return {
            "avg": float(
                self.measured_pods / self.measure_seconds
                if self.measure_seconds
                else a.mean()
            ),
            "p50": float(np.percentile(a, 50)),
            "p90": float(np.percentile(a, 90)),
            "p99": float(np.percentile(a, 99)),
            # cold-start honesty: the first measured batch usually carries
            # the XLA compile; "steady" drops it (time-weighted) so one
            # CLI run shows both the cold and the warm story
            "steady": float(self.steady_pods_per_sec()),
        }

    def latency_summary(self) -> dict[str, float]:
        """Per-pod e2e schedule-latency percentiles (BASELINE.md's 'p99
        per-pod schedule latency' metric) over the measured phases."""
        if not self.pod_latencies:
            return {"p50": 0.0, "p90": 0.0, "p99": 0.0}
        a = np.asarray(self.pod_latencies)
        return {
            "p50": float(np.percentile(a, 50)),
            "p90": float(np.percentile(a, 90)),
            "p99": float(np.percentile(a, 99)),
        }

    def check_threshold(self) -> None:
        """scheduler_perf.go's per-workload threshold assert, gated on
        POST-WARMUP steady-state pods/s: the avg was dominated by the
        first measured batch's compile stall, so one slow compile could
        flake the gate while a genuine sustained regression hid under a
        fast compile — the steady number is what the floor protects."""
        if self.threshold and (self.batch_samples or self.measure_seconds):
            if self.steady_pods_per_sec() < self.threshold:
                self.passed = False


def _resolve_count(op: Mapping, params: Mapping) -> int:
    if "countParam" in op:
        return int(params[op["countParam"].lstrip("$")])
    return int(op.get("count") or 0)


def _load_template(
    op: Mapping, key: str, base_dir: Path, default: Mapping
) -> Mapping:
    inline = op.get(f"{key}Template")
    if inline:
        return inline
    path = op.get(f"{key}TemplatePath")
    if path:
        with open(base_dir / path) as f:
            return yaml.safe_load(f)
    return default


def _instantiate(template: Mapping, index: int, prefix: str) -> dict:
    import json

    d = json.loads(json.dumps(template).replace("{{.Index}}", str(index)))
    meta = d.setdefault("metadata", {})
    if meta.get("generateName"):
        meta["name"] = f"{meta['generateName']}{index}"
    elif not meta.get("name"):
        meta["name"] = f"{prefix}-{index}"
    elif "{{.Index}}" not in ((template.get("metadata") or {}).get("name") or ""):
        # fixed template name: suffix the index so objects stay unique
        meta["name"] = f"{meta['name']}-{index}"
    return d


class PerfRunner:
    def __init__(
        self,
        config: SchedulerConfig | None = None,
        base_dir: str | Path = ".",
        pipelined: bool = True,
    ):
        self.config = config or SchedulerConfig()
        self.base_dir = Path(base_dir)
        self.pipelined = pipelined

    def run_file(
        self, path: str | Path, workload_filter: str | None = None
    ) -> list[WorkloadResult]:
        with open(path) as f:
            cases = yaml.safe_load(f)
        base = Path(path).parent
        out = []
        for case in cases:
            for wl in case.get("workloads") or [{"name": "default", "params": {}}]:
                if workload_filter and wl["name"] != workload_filter:
                    continue
                params = wl.get("params") or {}
                out.append(
                    self.run_workload(
                        case["name"],
                        wl["name"],
                        case.get("workloadTemplate") or [],
                        params,
                        base,
                        # upstream puts the throughput floor on the
                        # workload entry (scheduler_perf threshold field);
                        # accept it in params too
                        threshold=float(
                            wl.get("threshold")
                            or params.get("threshold")
                            or 0.0
                        ),
                    )
                )
        return out

    def run_workload(
        self,
        case_name: str,
        wl_name: str,
        ops: list[Mapping],
        params: Mapping[str, Any],
        base_dir: Path | None = None,
        threshold: float = 0.0,
    ) -> WorkloadResult:
        base_dir = base_dir or self.base_dir
        cluster = ClusterState()
        sched = Scheduler(cluster, self.config)
        res = WorkloadResult(
            test_case=case_name, workload=wl_name, threshold=threshold
        )
        node_seq = 0
        pod_seq = 0

        def consume(r, measure: bool, prev_at: float) -> float:
            n = len(r.scheduled)
            res.scheduled += n
            res.unschedulable += len(r.unschedulable)
            res.solve_seconds += r.solve_seconds
            at = r.completed_at or time.perf_counter()
            if measure and n:
                dt = max(at - prev_at, 1e-9)
                res.samples.append(n / dt)
                res.batch_samples.append((dt, n))
                res.measured_pods += n
                res.pod_latencies.extend(r.e2e_latencies)
            return at

        def drain(measure: bool) -> None:
            t0 = time.perf_counter()
            prev_at = t0
            while True:
                if self.pipelined:
                    results = sched.run_pipelined()
                else:
                    results = [sched.schedule_batch()]
                got_sched = False
                got_any = False
                for r in results:
                    prev_at = consume(r, measure, prev_at)
                    got_sched = got_sched or bool(r.scheduled)
                    got_any = got_any or r.progressed
                if not got_any:
                    break
                if not got_sched:
                    break  # only stuck pods remain
            if measure:
                res.measure_seconds += time.perf_counter() - t0

        for op in ops:
            opcode = op.get("opcode")
            if opcode == "createNodes":
                count = _resolve_count(op, params)
                tpl = _load_template(op, "node", base_dir, DEFAULT_NODE)
                for _ in range(count):
                    cluster.create_node(
                        Node.from_dict(_instantiate(tpl, node_seq, "node"))
                    )
                    node_seq += 1
            elif opcode == "createPods":
                count = _resolve_count(op, params)
                tpl = _load_template(op, "pod", base_dir, DEFAULT_POD)
                ns = op.get("namespace")
                measure = bool(op.get("collectMetrics"))
                for _ in range(count):
                    d = _instantiate(tpl, pod_seq, "pod")
                    if ns:
                        d.setdefault("metadata", {})["namespace"] = ns
                    cluster.create_pod(Pod.from_dict(d))
                    pod_seq += 1
                drain(measure)
            elif opcode == "createPodsSteady":
                # open-loop sustained workload (VERDICT r4 #2): pods
                # ARRIVE at a fixed rate while the scheduler drains
                # concurrently, so throughput and the per-pod e2e p99
                # reflect queueing under load, not closed-loop batching.
                # Interleaved single-threaded: create every arrival that
                # is due by wall clock, then run a bounded pipelined
                # burst, repeat (the 1-vCPU host's analog of the
                # creator-goroutine + scheduler race in scheduler_perf).
                count = _resolve_count(op, params)
                rate = float(
                    op.get("ratePodsPerSec")
                    or params.get(
                        str(op.get("rateParam", "")).lstrip("$") or "", 0
                    )
                    or 1000.0
                )
                tpl = _load_template(op, "pod", base_dir, DEFAULT_POD)
                measure = bool(op.get("collectMetrics"))
                t0 = time.perf_counter()
                prev_at = t0
                created = 0
                while created < count or sched.pending:
                    due = min(
                        count, int((time.perf_counter() - t0) * rate) + 1
                    )
                    while created < due:
                        cluster.create_pod(
                            Pod.from_dict(_instantiate(tpl, pod_seq, "pod"))
                        )
                        pod_seq += 1
                        created += 1
                    made_progress = False
                    for r in (
                        sched.run_pipelined(max_batches=2)
                        if self.pipelined
                        else [sched.schedule_batch()]
                    ):
                        prev_at = consume(r, measure, prev_at)
                        made_progress = made_progress or r.progressed
                    if created >= count and not made_progress:
                        break  # drained (or only stuck pods remain)
                if measure:
                    res.measure_seconds += time.perf_counter() - t0
            elif opcode == "createNamespaces":
                pass  # namespaces are implicit in this state service
            elif opcode == "barrier":
                drain(False)
            elif opcode == "sleep":
                time.sleep(float(op.get("duration") or 0))
            elif opcode == "churn":
                # background create/delete between batches; the interleaved
                # batches may also bind earlier pending pods, so their
                # results count toward the workload totals
                number = int(op.get("number") or 1)
                tpl = _load_template(op, "pod", base_dir, DEFAULT_POD)
                for _ in range(number):
                    d = _instantiate(tpl, pod_seq, "churn")
                    pod_seq += 1
                    created = cluster.create_pod(Pod.from_dict(d))
                    r = sched.schedule_batch()
                    res.scheduled += len(r.scheduled)
                    res.unschedulable += len(r.unschedulable)
                    res.solve_seconds += r.solve_seconds
                    try:
                        cluster.delete_pod(created.namespace, created.name)
                    except Exception:
                        pass
            else:
                raise ValueError(f"unsupported opcode {opcode!r}")
        res.check_threshold()
        return res
