"""Every declared reference-name metric must be OBSERVED, not merely
declared (VERDICT r2 weak #3: dashboards built on the reference names
would have shown empty series)."""

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def test_all_declared_series_observed():
    clock = FakeClock()
    cs = ClusterState()
    for i in range(4):
        b = (
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
            .label(ZONE, f"z{i % 2}")
            .label(HOST, f"n{i}")
        )
        cs.create_node(b.obj())
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )

    # successes across the plugin families (drives the per-plugin
    # tensorizer timings + extension points + SLIs)
    cs.create_pod(
        MakePod().name("web").label("app", "w").req({"cpu": "500m"})
        .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "w"}).obj()
    )
    cs.create_pod(
        MakePod().name("anti").label("app", "a").req({"cpu": "500m"})
        .pod_anti_affinity(HOST, {"app": "a"}).obj()
    )
    cs.create_pod(MakePod().name("ported").req({"cpu": "250m"}).host_port(8080).obj())
    # a victim + preemptor (drives PostFilter + preemption series)
    cs.create_pod(MakePod().name("victim").priority(0).req({"cpu": "4"}).obj())
    cs.bind("default", "victim", "n0")
    cs.create_pod(
        MakePod().name("preemptor").priority(10)
        .node_selector({HOST: "n0"}).req({"cpu": "4"}).obj()
    )
    # a never-fits pod (unschedulable series) and a gated pod
    cs.create_pod(MakePod().name("huge").req({"cpu": "64"}).obj())
    cs.create_pod(
        MakePod().name("gated").req({"cpu": "100m"})
        .scheduling_gates(["wait"]).obj()
    )

    sched.schedule_batch()
    clock.advance(15.0)  # backoff completes -> BackoffComplete series
    sched.schedule_batch()
    clock.advance(15.0)
    sched.schedule_batch()

    # an out-of-tree plugin scheduler: drives the fold memo counter
    from kubernetes_tpu.framework.interface import FilterPlugin, Status

    class AnyNode(FilterPlugin):
        def filter(self, state, pod, node, placed=()):
            return Status.success()

    cs2 = ClusterState()
    cs2.create_node(
        MakeNode().name("m0").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
    )
    sched2 = Scheduler(
        cs2,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first"),
            out_of_tree_plugins=(AnyNode(),),
        ),
        clock=clock,
    )
    cs2.create_pod(MakePod().name("f1").req({"cpu": "100m"}).obj())
    sched2.schedule_batch()  # fold miss
    cs2.create_pod(MakePod().name("f2").req({"cpu": "100m"}).obj())
    sched2.schedule_batch()  # fold hit

    text = metrics.render().decode()
    declared = [
        "scheduler_schedule_attempts_total",
        "scheduler_scheduling_attempt_duration_seconds",
        "scheduler_pod_scheduling_attempts",
        "scheduler_pod_scheduling_sli_duration_seconds",
        "scheduler_framework_extension_point_duration_seconds",
        "scheduler_plugin_execution_duration_seconds",
        "scheduler_pending_pods",
        "scheduler_queue_incoming_pods_total",
        "scheduler_preemption_attempts_total",
        "scheduler_plugin_fold_cache_total",
        "scheduler_preemption_victims",
        "scheduler_tpu_solve_latency_seconds",
        "scheduler_tpu_solve_batch_size",
        "scheduler_tpu_tensorize_seconds",
    ]
    missing = []
    for name in declared:
        # a SAMPLE line (name followed by '{' or space/suffix), not just
        # the # HELP header prometheus_client always prints
        if not any(
            line.startswith(name) and not line.startswith("#")
            for line in text.splitlines()
        ):
            missing.append(name)
    assert not missing, f"declared but never observed: {missing}"

    # spot-check semantic content
    assert 'extension_point="Filter"' in text
    assert 'extension_point="PostFilter"' in text
    assert 'plugin="PodTopologySpread"' in text
    assert 'plugin="InterPodAffinity"' in text
    assert 'event="BackoffComplete"' in text
    assert 'queue="unschedulable"' in text


def test_score_disable_is_separate_from_filter_disable():
    """weak r2 #7: plugins.score.disabled and plugins.filter.disabled are
    independent stages — score-disabling InterPodAffinity zeroes its weight
    while its Filter stage still blocks."""
    from kubernetes_tpu.config import types as config_types

    yaml_doc = """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
    plugins:
      score:
        disabled:
          - name: InterPodAffinity
"""
    import tempfile, os

    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(yaml_doc)
        path = f.name
    try:
        cfg = config_types.load_file(path)
        sc = config_types.scheduler_config(cfg)
        assert sc.solver.interpod_weight == 0  # score stage off
        assert "InterPodAffinity" not in sc.solver.disabled_filters  # filter on
    finally:
        os.unlink(path)
