"""PodTopologySpread defaultingType=System: service-selected pods with no
explicit constraints get the soft zone/hostname cluster defaults
(podtopologyspread/common.go#buildDefaultConstraints +
helper/spread.go#DefaultSelector, VERDICT r1 #7)."""

from kubernetes_tpu.api.objects import Service
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import spread as osp
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState


def test_system_defaults_need_matching_service():
    pod = MakePod().name("p").label("app", "web").obj()
    svc = Service(name="web", selector={"app": "web"})
    other = Service(name="db", selector={"app": "db"})
    assert osp.system_default_constraints(pod, [other]) == []
    cs = osp.system_default_constraints(pod, [svc, other])
    assert [c.topology_key for c in cs] == [
        "topology.kubernetes.io/zone",
        "kubernetes.io/hostname",
    ]
    assert [c.max_skew for c in cs] == [3, 5]
    assert all(c.selector.matches({"app": "web"}) for c in cs)
    # a pod with its own constraints never gets defaults
    podc = (
        MakePod().name("p2").label("app", "web")
        .spread_constraint(1, "zone", "ScheduleAnyway", {"app": "web"}).obj()
    )
    assert osp.system_default_constraints(podc, [svc]) == []
    # defaults are soft: the hard path never sees them
    assert osp.effective_constraints(pod, hard=True, defaults=cs) == []
    assert osp.effective_constraints(pod, hard=False, defaults=cs) == list(cs)


def _run(with_service: bool) -> dict[str, int]:
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("big-a").capacity({"cpu": "16", "memory": "64Gi", "pods": "50"})
        .label("topology.kubernetes.io/zone", "a")
        .label("kubernetes.io/hostname", "big-a").obj()
    )
    cs.create_node(
        MakeNode().name("small-b").capacity({"cpu": "4", "memory": "16Gi", "pods": "50"})
        .label("topology.kubernetes.io/zone", "b")
        .label("kubernetes.io/hostname", "small-b").obj()
    )
    if with_service:
        cs.create_service(Service(name="web", selector={"app": "web"}))
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16, solver=ExactSolverConfig(tie_break="first")
        ),
    )
    for i in range(6):
        cs.create_pod(
            MakePod().name(f"w-{i}").label("app", "web")
            .req({"cpu": "250m", "memory": "256Mi"}).obj()
        )
    r = sched.schedule_batch()
    counts: dict[str, int] = {"big-a": 0, "small-b": 0}
    for _, node in r.scheduled:
        counts[node] += 1
    assert sum(counts.values()) == 6
    return counts


def test_system_defaults_spread_service_pods():
    # without a service: LeastAllocated piles pods onto the big node
    skewed = _run(with_service=False)
    assert skewed["big-a"] > skewed["small-b"] + 1
    # with the service: soft zone/hostname defaults balance the zones
    balanced = _run(with_service=True)
    assert abs(balanced["big-a"] - balanced["small-b"]) <= 1


def test_mixed_service_membership_does_not_share_class():
    """Pods identical except labels — one selected by a service, one not —
    must not collapse into one scheduling class: only the selected pod gets
    the System default spreading."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("big-a").capacity({"cpu": "16", "memory": "64Gi", "pods": "50"})
        .label("topology.kubernetes.io/zone", "a")
        .label("kubernetes.io/hostname", "big-a").obj()
    )
    cs.create_node(
        MakeNode().name("small-b").capacity({"cpu": "4", "memory": "16Gi", "pods": "50"})
        .label("topology.kubernetes.io/zone", "b")
        .label("kubernetes.io/hostname", "small-b").obj()
    )
    cs.create_service(Service(name="web", selector={"app": "web"}))
    sched = Scheduler(
        cs,
        SchedulerConfig(batch_size=16, solver=ExactSolverConfig(tie_break="first")),
    )
    # 4 service pods (spread) interleaved with 4 free pods (least-allocated)
    for i in range(4):
        cs.create_pod(
            MakePod().name(f"w-{i}").label("app", "web")
            .req({"cpu": "250m", "memory": "256Mi"}).obj()
        )
        cs.create_pod(
            MakePod().name(f"f-{i}").label("app", "batch")
            .req({"cpu": "250m", "memory": "256Mi"}).obj()
        )
    r = sched.schedule_batch()
    web = {n for k, n in r.scheduled if k.startswith("default/w-")}
    free = [n for k, n in r.scheduled if k.startswith("default/f-")]
    # service pods were zone-balanced; free pods favored the big node
    assert web == {"big-a", "small-b"}
    assert free.count("big-a") > free.count("small-b")
