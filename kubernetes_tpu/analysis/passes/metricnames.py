"""MET001 — metric usage must resolve against the metrics registry.

Every ``metrics.<attr>`` reference in scheduler.py / server/ / solver/
must be an attribute actually defined in ``kubernetes_tpu/metrics``
(the module registers against a dedicated CollectorRegistry, so a typo
does not fail at import — it raises AttributeError on the first hot
batch that tries to record it). String literals shaped like a
prometheus series name (``scheduler_*``) must likewise name a
registered series, so dashboards never chase a renamed metric.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import Finding, Pass

_NAME_RE = re.compile(r"scheduler_[a-z0-9_]+")
_METRIC_CLASSES = {"Counter", "Gauge", "Histogram", "Summary"}


def load_metric_registry(path: Path | None = None) -> dict[str, str | None]:
    """attr name -> prometheus series name (None for non-metric module
    globals like REGISTRY / render, which are still valid attributes)."""
    if path is None:
        path = (
            Path(__file__).resolve().parents[2] / "metrics" / "__init__.py"
        )
    attrs: dict[str, str | None] = {}
    tree = ast.parse(path.read_text(), filename=str(path))
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            attrs[stmt.name] = None
        elif isinstance(stmt, ast.Assign):
            name = None
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    name = t.id
            if name is None:
                continue
            series = None
            v = stmt.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id in _METRIC_CLASSES
                and v.args
                and isinstance(v.args[0], ast.Constant)
                and isinstance(v.args[0].value, str)
            ):
                series = v.args[0].value
            attrs[name] = series
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                attrs[alias.asname or alias.name.split(".")[0]] = None
    return attrs


class MetricNamePass(Pass):
    rule = "MET001"
    title = "unregistered metric reference"

    def run(self, module, ctx):
        if not any(module.rel.startswith(p) for p in ctx.metric_scan_paths):
            return []
        attrs = ctx.metric_attrs
        if attrs is None:
            attrs = ctx.metric_attrs = load_metric_registry()
        series = {s for s in attrs.values() if s}
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "metrics"
            ):
                if node.attr not in attrs:
                    findings.append(
                        Finding(
                            self.rule, module.path, node.lineno,
                            f"metrics.{node.attr} is not defined in "
                            "kubernetes_tpu/metrics/__init__.py",
                            hint="register the series there (dedicated "
                            "registry) before recording to it",
                        )
                    )
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _NAME_RE.fullmatch(node.value)
                and node.value not in series
            ):
                findings.append(
                    Finding(
                        self.rule, module.path, node.lineno,
                        f'metric name string "{node.value}" does not match '
                        "any registered series",
                        hint="dashboards key on exposition names; register "
                        "or correct the series name",
                    )
                )
        return findings
