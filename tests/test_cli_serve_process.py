"""Process-level smoke of the serve binary: `python -m kubernetes_tpu
serve` in a real subprocess — the operator's actual entry point — must
come up, answer verbs, ingest, schedule, and die cleanly."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _req(port, method, path, payload=None, timeout=120):
    # generous default: the first device-backed verb compiles the evaluator
    data = json.dumps(payload).encode() if payload is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=data,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_serve_process_end_to_end(tmp_path):
    state = {
        "nodes": [
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .obj().to_dict()
            for i in range(4)
        ],
    }
    state_file = tmp_path / "state.json"
    state_file.write_text(json.dumps(state))
    port = _free_port()

    env = dict(os.environ)
    # the server subprocess should run on CPU in tests; note this box's
    # jax+axon build ignores the env var and uses the TPU — both work
    env["JAX_PLATFORMS"] = "cpu"
    log = open(tmp_path / "serve.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kubernetes_tpu", "serve",
            "--state", str(state_file),
            "--mode", "scheduler",
            "--port", str(port),
        ],
        cwd=_REPO,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )

    def server_log() -> str:
        log.flush()
        return (tmp_path / "serve.log").read_text()

    try:
        last_err = None
        for _ in range(240):
            try:
                # healthz is plain text ("ok"), not JSON
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5
                ) as resp:
                    assert resp.read() == b"ok"
                break
            except Exception as e:
                last_err = e
                if proc.poll() is not None:
                    pytest.fail(
                        "serve exited during startup:\n" + server_log()
                    )
                time.sleep(0.5)
        else:
            pytest.fail(
                f"serve never became healthy (last: {last_err!r}):\n"
                + server_log()
            )

        st = _req(port, "GET", "/api/state")
        assert st["nodes"] == 4

        # webhook verb over the real socket
        pod = MakePod().name("probe").req({"cpu": "4"}).obj()
        out = _req(
            port, "POST", "/filter",
            {"pod": pod.to_dict(), "nodenames": ["n0", "n1", "ghost"]},
        )
        assert out["nodenames"] == ["n0", "n1"]
        assert out["failedAndUnresolvableNodes"] == {"ghost": "node not found"}

        # ingest + background scheduling
        pods = {
            "items": [
                MakePod().name(f"w{i}").req({"cpu": "1"}).obj().to_dict()
                for i in range(6)
            ]
        }
        assert _req(port, "POST", "/api/pods", pods) == {"applied": 6}
        for _ in range(120):
            st = _req(port, "GET", "/api/state")
            if st["unscheduled"] == 0:
                break
            time.sleep(0.5)
        assert st["unscheduled"] == 0

        # metrics exposition is live
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "scheduler_schedule_attempts_total" in raw
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _wait_healthy(proc, port, server_log):
    last_err = None
    for _ in range(240):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                assert resp.read() == b"ok"
            return
        except Exception as e:
            last_err = e
            if proc.poll() is not None:
                pytest.fail(
                    "serve exited during startup:\n" + server_log()
                )
            time.sleep(0.5)
    pytest.fail(
        f"serve never became healthy (last: {last_err!r}):\n"
        + server_log()
    )


def _get_status(port, path):
    """(status, parsed-JSON body) — urllib raises on 4xx/5xx, but the
    debug surfaces' disabled contracts ARE json bodies with status."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_serve_debug_surfaces_end_to_end(tmp_path):
    """ISSUE 18 satellite: the operator debug surfaces — /debug/slo,
    /debug/hub, /debug/profile — over a real serve subprocess with the
    full telemetry stack on: status codes, response schema, and one
    consistent-snapshot read of /debug/profile under concurrent
    scheduling traffic."""
    from kubernetes_tpu.obs.profile import STAGES

    state = {
        "nodes": [
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "40"})
            .obj().to_dict()
            for i in range(4)
        ],
    }
    state_file = tmp_path / "state.json"
    state_file.write_text(json.dumps(state))
    port = _free_port()

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    log = open(tmp_path / "serve.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kubernetes_tpu", "serve",
            "--state", str(state_file),
            "--mode", "scheduler",
            "--port", str(port),
            "--obs", "--slo", "30", "--telemetry",
        ],
        cwd=_REPO,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )

    def server_log() -> str:
        log.flush()
        return (tmp_path / "serve.log").read_text()

    try:
        _wait_healthy(proc, port, server_log)

        # /debug/slo: enabled (serve --slo 30), serves the engine's
        # live snapshot schema
        status, slo = _get_status(port, "/debug/slo")
        assert status == 200, slo
        for key in ("healthy", "p99_pod_latency_s", "burn_rates"):
            assert key in slo, sorted(slo)

        # /debug/hub: this serve is not a fleet replica — the disabled
        # contract is a 404 WITH a json error body, not a bare error
        status, hub = _get_status(port, "/debug/hub")
        assert status == 404
        assert "occupancy hub" in hub["error"]

        # /debug/profile: enabled (serve --telemetry) even before any
        # batch ran — the schema must hold at zero
        status, prof = _get_status(port, "/debug/profile")
        assert status == 200, prof
        assert prof["enabled"] is True
        assert set(prof["profile"]["stage_seconds"]) == set(STAGES)
        assert "degraded" in prof["sentinel"]
        assert "captures" in prof["bundles"]

        # consistent snapshots under concurrent traffic: ingest pods
        # (the drain task schedules them in the background) while
        # polling the profile surface — every poll must parse against
        # the schema and the batch counter must be monotone
        pods = {
            "items": [
                MakePod().name(f"w{i}").req({"cpu": "1"}).obj().to_dict()
                for i in range(24)
            ]
        }
        assert _req(port, "POST", "/api/pods", pods) == {"applied": 24}
        last_batches = 0
        for _ in range(120):
            status, prof = _get_status(port, "/debug/profile")
            assert status == 200
            batches = prof["profile"]["batches"]
            assert batches >= last_batches, (
                "profiler batch counter went backwards under "
                f"concurrent reads: {last_batches} -> {batches}"
            )
            assert set(prof["profile"]["stage_seconds"]) == set(STAGES)
            last_batches = batches
            st = _req(port, "GET", "/api/state")
            if st["unscheduled"] == 0 and batches > 0:
                break
            time.sleep(0.5)
        assert st["unscheduled"] == 0
        assert last_batches > 0, "no batch ever closed a ledger entry"
        # the scheduled batches must have attributed stage time
        assert sum(prof["profile"]["stage_seconds"].values()) > 0.0

        # ?capture=1: a manual forensic capture counts (no bundle_dir,
        # so nothing hits disk — captures counts regardless)
        status, cap = _get_status(port, "/debug/profile?capture=1")
        assert status == 200
        assert cap["captured"] is True
        assert cap["bundles"]["captures"] >= 1
        assert cap["bundles"]["by_trigger"].get("manual", 0) >= 1
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
