"""Tuned config in, standard config out (the ROADMAP item-5 discipline).

A scheduler that converged under the tuning runtime can pin the result:
``tuned_profile`` emits a standard ``KubeSchedulerConfiguration``-shaped
document whose ``tpuSolver`` (and, for fleet replicas, ``fleet``) keys
carry the tuned knob values — alongside the live solver settings the
knobs were tuned UNDER (batchSize, groupSize, meshDevices, tieBreak,
pallas: a tuned chunk size chosen for group 512 on an 8-way mesh is
meaningless under different ones) — with the ``tuning`` section
disabled. The document round-trips through ``config.types.load`` +
``scheduler_config`` into the same tuned hot path with zero tuning
machinery at runtime (tested in tests/test_tuning.py). Scope: this is
the SOLVER surface; profiles/extenders/rebalance sections are the
operator's own and should be merged from their deployment config. No
new config dialect: every value lands on exactly the key an operator
would hand-set.
"""

from __future__ import annotations

from .runtime import (
    KNOB_CHUNK,
    KNOB_FLUSH,
    KNOB_SPLIT,
    KNOB_STREAM_DEPTH,
)

API_VERSION = "kubescheduler.config.k8s.io/v1"


def tuned_profile(scheduler) -> dict:
    """The standard-config document pinning ``scheduler``'s tuned knob
    values. Untuned knobs fall back to the scheduler's live config (the
    document is complete either way — loading it reproduces the running
    configuration, tuned or not)."""
    tuner = scheduler.tuner
    knobs = tuner.knob_values() if tuner is not None else {}
    cfg = scheduler.config
    doc: dict = {
        "apiVersion": API_VERSION,
        "kind": "KubeSchedulerConfiguration",
        "tpuSolver": {
            # the live solver settings the knobs were tuned under —
            # without them the pinned knob values describe a hot path
            # that no longer exists
            "batchSize": cfg.batch_size,
            "groupSize": scheduler.solver.config.group_size,
            "meshDevices": cfg.mesh_devices,
            "tieBreak": scheduler.solver.config.tie_break,
            "enablePreemption": cfg.enable_preemption,
            "pallas": scheduler.solver.config.pallas,
            # the tuned knobs (live config where untuned)
            "streamDepth": int(
                knobs.get(KNOB_STREAM_DEPTH, cfg.stream_depth)
            ),
            "pipelineSplit": int(
                knobs.get(KNOB_SPLIT, cfg.pipeline_split)
            ),
            "backlogChunkPods": int(
                knobs.get(KNOB_CHUNK, cfg.backlog_chunk_pods)
            ),
        },
        # the emitted document is the STATIC pin: a scheduler loading
        # it runs the tuned values with the tuner off
        "tuning": {"enabled": False},
    }
    if scheduler.fleet is not None:
        flush = knobs.get(KNOB_FLUSH, scheduler.fleet.flush_batch())
        fleet_section: dict = {
            # fleet validation requires the replica identity whenever
            # any fleet key is set
            "replica": scheduler.fleet.replica,
        }
        if flush is not None:
            fleet_section["flushBatch"] = int(flush)
        doc["fleet"] = fleet_section
    return doc


def dump_yaml(doc: dict) -> str:
    import yaml

    return yaml.safe_dump(doc, sort_keys=True)
