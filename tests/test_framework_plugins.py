"""The extension-point-shaped plugin API (SURVEY §8.2; VERDICT r2 L5c's
"still missing" item): framework/interface.py + runtime.py as the
upstream-test-shaped fixture, and out-of-tree plugins folded into the
device solve via SchedulerConfig.out_of_tree_plugins."""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.framework import (
    CycleState,
    FilterPlugin,
    Framework,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.framework.interface import Registry
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock


class OddNodesOnly(FilterPlugin):
    """Rejects nodes with an even trailing index."""

    def filter(self, state, pod, node, placed=()):
        if int(node.name.rsplit("-", 1)[-1]) % 2 == 0:
            return Status.unschedulable("even node")
        return Status.success()


class PreferHighIndex(ScorePlugin):
    def __init__(self, weight=5):
        self._w = weight

    def score(self, state, pod, node):
        return min(int(node.name.rsplit("-", 1)[-1]) * 10, 100)

    def weight(self):
        return self._w


def mk_nodes(n=6):
    return [
        MakeNode()
        .name(f"n-{i}")
        .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
        .obj()
        for i in range(n)
    ]


# -- the host-side runtime (the upstream-test fixture shape) ----------------


def test_framework_run_all_with_custom_plugins():
    fw = Framework(
        nodes=mk_nodes(),
        registry=Registry(
            filter=[OddNodesOnly()], score=[PreferHighIndex()]
        ),
    )
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    feasible, scores, st = fw.run_all(pod)
    assert st.is_success
    assert [n.name for n in feasible] == ["n-1", "n-3", "n-5"]
    # custom score steers toward the highest index among feasible
    assert max(scores, key=scores.get) == "n-5"


def test_framework_cycle_state_and_status():
    state = CycleState()
    state.write("k", {"x": 1})
    assert state.read("k") == {"x": 1}
    clone = state.clone()
    clone.write("k", "other")
    assert state.read("k") == {"x": 1}  # clone is independent
    with pytest.raises(KeyError):
        state.read("missing")
    assert Status.unschedulable("r").is_rejection
    assert not Status.error("boom").is_rejection


def test_framework_rejects_out_of_range_scores():
    class Bad(ScorePlugin):
        def score(self, state, pod, node):
            return 101

    fw = Framework(nodes=mk_nodes(2), registry=Registry(score=[Bad()]))
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    with pytest.raises(ValueError):
        fw.run_score_plugins(CycleState(), pod, list(fw.nodes))


def test_framework_in_tree_pipeline_included():
    """with_default_plugins: in-tree filters run before custom ones."""
    nodes = mk_nodes(3)
    fw = Framework(nodes=nodes)
    big = MakePod().name("big").req({"cpu": "64"}).obj()
    feasible, _, st = fw.run_all(big)
    assert not feasible and st.is_rejection


# -- out-of-tree plugins inside the device solve ----------------------------


def _sched(cs, plugins, group=64):
    return Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            out_of_tree_plugins=tuple(plugins),
        ),
        clock=FakeClock(),
    )


def test_out_of_tree_filter_gates_the_solve():
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [OddNodesOnly()])
    for i in range(4):
        cs.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 4
    for _, node_name in r.scheduled:
        assert int(node_name.rsplit("-", 1)[-1]) % 2 == 1


def test_out_of_tree_score_steers_the_solve():
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    # heavy custom weight dominates the default headroom scoring
    sched = _sched(cs, [PreferHighIndex(weight=50)])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/p") == "n-5"


class GoldOnly(FilterPlugin):
    """Label-sensitive filter: only tier=gold pods may use node n-5."""

    def filter(self, state, pod, node, placed=()):
        if node.name == "n-5" and pod.labels.get("tier") != "gold":
            return Status.unschedulable("n-5 reserved for gold")
        return Status.success()


def test_label_sensitive_plugin_splits_classes():
    """Two pods identical except for a label a custom plugin reads must
    NOT share one class representative's verdicts (review-caught)."""
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [GoldOnly(), PreferHighIndex(weight=50)])
    cs.create_pod(
        MakePod().name("gold").label("tier", "gold").req({"cpu": "1"}).obj()
    )
    cs.create_pod(
        MakePod().name("bronze").label("tier", "bronze").req({"cpu": "1"}).obj()
    )
    r = sched.schedule_batch()
    placed = dict(r.scheduled)
    assert placed.get("default/gold") == "n-5"
    assert placed.get("default/bronze") not in (None, "n-5")


def test_error_status_aborts_instead_of_masking():
    class Flaky(FilterPlugin):
        def filter(self, state, pod, node, placed=()):
            return Status.error("backend down")

    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [Flaky()])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    with pytest.raises(RuntimeError, match="backend down"):
        sched.schedule_batch()


def test_out_of_tree_plugins_work_with_grouped_path():
    """Identical pods (grouped fast path) must also see custom tables —
    extra scores fold into the frontier table like ImageLocality."""
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [OddNodesOnly(), PreferHighIndex(weight=50)], group=4)
    for i in range(8):
        cs.create_pod(MakePod().name(f"w{i}").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 8
    landed = {node for _, node in r.scheduled}
    assert all(int(n.rsplit("-", 1)[-1]) % 2 == 1 for n in landed)
    # first pods go to n-5 until headroom drops below the custom margin
    assert dict(r.scheduled)["default/w0"] == "n-5"


# -- the full extension-point surface (VERDICT r3 #3) ------------------------


from kubernetes_tpu.framework.interface import (
    PostBindPlugin,
    PostFilterPlugin,
    PreBindPlugin,
    PreEnqueuePlugin,
    PreFilterPlugin,
    PreFilterResult,
    PermitPlugin,
    QueueSortPlugin,
    ReservePlugin,
    StatusCode,
)


class AllowlistN3(PreFilterPlugin):
    """PreFilterResult node-name allowlist: only n-3 is a candidate."""

    def pre_filter(self, state, pod):
        return Status.success(), PreFilterResult(frozenset({"n-3"}))


def test_pre_filter_result_allowlist_folds_into_mask():
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [AllowlistN3()])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert dict(r.scheduled) == {"default/p": "n-3"}


def test_pre_filter_rejection_fails_pod_on_all_nodes():
    class NoDice(PreFilterPlugin):
        def pre_filter(self, state, pod):
            return Status.unschedulable("quota exhausted")

    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [NoDice()])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/p"] and not r.scheduled


class TierGate(PreEnqueuePlugin):
    """Gates pods until the (mutable) gate opens."""

    def __init__(self):
        self.open = False

    def pre_enqueue(self, pod):
        return Status.success() if self.open else Status.unschedulable("closed")


def test_pre_enqueue_gates_and_releases():
    gate = TierGate()
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [gate])
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    cs.create_pod(pod)
    assert sched.queue.pending_counts()["gated"] == 1
    assert not sched.schedule_batch().scheduled  # parked, nothing pops
    gate.open = True
    # a pod update re-evaluates PreEnqueue (scheduling_queue semantics)
    sched.queue.update(pod)
    r = sched.schedule_batch()
    assert len(r.scheduled) == 1


class ByNameOrder(QueueSortPlugin):
    def less(self, info1, info2):
        return info1.pod.name < info2.pod.name


def test_queue_sort_plugin_controls_pop_order():
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [ByNameOrder()])
    for name, prio in (("c", 100), ("a", 0), ("b", 50)):
        cs.create_pod(
            MakePod().name(name).priority(prio).req({"cpu": "1"}).obj()
        )
    r = sched.schedule_batch()
    # custom order by name beats the default PrioritySort (c would pop
    # first by priority)
    assert [k for k, _ in r.scheduled] == [
        "default/a", "default/b", "default/c"
    ]


def test_two_queue_sort_plugins_rejected():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="at most one QueueSortPlugin"):
        Registry.classify([ByNameOrder(), ByNameOrder()])


class NominateAnyway(PostFilterPlugin):
    def __init__(self, node_name):
        self._n = node_name
        self.calls = 0

    def post_filter(self, state, pod, filtered_nodes):
        self.calls += 1
        assert filtered_nodes  # NodeToStatusMap analog is populated
        return self._n, Status.success()


def test_post_filter_runs_on_failure_and_nominates():
    pf = NominateAnyway("n-1")
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [pf])
    cs.create_pod(MakePod().name("huge").req({"cpu": "64"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/huge"]
    assert pf.calls == 1
    assert cs.get_pod("default", "huge").nominated_node_name == "n-1"


class Recorder(ReservePlugin, PreBindPlugin, PostBindPlugin):
    """One object on Reserve+PreBind+PostBind, recording call order."""

    def __init__(self, fail_pre_bind=False):
        self.calls = []
        self.fail_pre_bind = fail_pre_bind

    def reserve(self, state, pod, node_name):
        self.calls.append(("reserve", pod.name, node_name))
        return Status.success()

    def unreserve(self, state, pod, node_name):
        self.calls.append(("unreserve", pod.name, node_name))

    def pre_bind(self, state, pod, node_name):
        self.calls.append(("pre_bind", pod.name, node_name))
        if self.fail_pre_bind:
            return Status.unschedulable("pre-bind veto")
        return Status.success()

    def post_bind(self, state, pod, node_name):
        self.calls.append(("post_bind", pod.name, node_name))


def test_reserve_pre_bind_post_bind_order():
    rec = Recorder()
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [rec])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 1
    assert [c[0] for c in rec.calls] == ["reserve", "pre_bind", "post_bind"]


def test_pre_bind_failure_unreserves_and_requeues():
    rec = Recorder(fail_pre_bind=True)
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [rec])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert not r.scheduled
    assert [c[0] for c in rec.calls] == ["reserve", "pre_bind", "unreserve"]
    assert r.bind_failures and "pre-bind veto" in r.bind_failures[0][1]
    # the assume rolled back: nothing occupies the node in cache
    assert not sched.cache.is_assumed("default/p")


class HoldAtPermit(PermitPlugin):
    def __init__(self, timeout=30.0):
        self.timeout = timeout

    def permit(self, state, pod, node_name):
        return Status(StatusCode.WAIT), self.timeout


def test_permit_wait_then_approve():
    rec = Recorder()
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [HoldAtPermit(), rec])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert not r.scheduled and not r.unschedulable
    waiting = sched.waiting_pods()
    assert list(waiting) == ["default/p"]
    wp = waiting["default/p"]
    assert wp.get_pending_plugins() == ["HoldAtPermit"]
    wp.allow("HoldAtPermit")
    r2 = sched.schedule_batch()
    assert [k for k, _ in r2.scheduled] == ["default/p"]
    assert cs.get_pod("default", "p").node_name
    # binding completed through PreBind/PostBind after the wait
    assert [c[0] for c in rec.calls] == ["reserve", "pre_bind", "post_bind"]


def test_permit_wait_then_timeout_requeues():
    rec = Recorder()
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [HoldAtPermit(timeout=10.0), rec])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    sched.schedule_batch()
    assert list(sched.waiting_pods()) == ["default/p"]
    sched.clock.advance(11.0)
    r2 = sched.schedule_batch()
    assert r2.unschedulable == ["default/p"] and not r2.scheduled
    assert not sched.waiting_pods()
    # rolled back: unreserve ran, pod unbound, parked for retry
    assert rec.calls[-1][0] == "unreserve"
    assert not cs.get_pod("default", "p").node_name
    assert sched.queue.pending_counts()["unschedulable"] == 1


def test_permit_reject_rolls_back():
    class Deny(PermitPlugin):
        def permit(self, state, pod, node_name):
            return Status.unschedulable("denied"), 0.0

    rec = Recorder()
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [Deny(), rec])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/p"]
    assert rec.calls[-1][0] == "unreserve"


def test_pre_enqueue_regates_on_requeue():
    """A mutable PreEnqueue plugin that closes AFTER a pod was admitted
    must re-gate the pod on its way back to the active queue (every
    moveToActiveQ path runs the PreEnqueue point, review-caught)."""
    gate = TierGate()
    gate.open = True
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [gate])
    cs.create_pod(MakePod().name("big").req({"cpu": "64"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/big"]  # admitted, failed, parked
    gate.open = False
    sched.clock.advance(301.0)  # force the unschedulable leftover flush
    r2 = sched.schedule_batch()
    assert not r2.scheduled and not r2.unschedulable
    assert sched.queue.pending_counts()["gated"] == 1


def test_modified_event_does_not_requeue_permit_waiting_pod():
    """A watch MODIFIED for a pod parked at Permit must not re-enter the
    queue (it is in flight: assumed + reserved — review-caught repro
    showed double-scheduling and a stale queue entry)."""
    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [HoldAtPermit()])
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    cs.create_pod(pod)
    sched.schedule_batch()
    assert list(sched.waiting_pods()) == ["default/p"]
    # external label update while waiting
    updated = cs.get_pod("default", "p")
    updated.labels = dict(updated.labels, touched="yes")
    cs.update_pod(updated)
    assert len(sched.queue) == 0  # NOT re-queued
    sched.waiting_pods()["default/p"].allow("HoldAtPermit")
    r = sched.schedule_batch()
    assert [k for k, _ in r.scheduled] == ["default/p"]
    assert sched.queue.pending_counts()["unschedulable"] == 0
    assert sched.pending == 0


def test_fold_cache_hits_on_identical_batches():
    """Two batches of identical pod classes against an unchanged cluster
    reuse the memoized fold (VERDICT r3 #8) — verdicts identical, the
    O(classes x nodes) Python pass skipped, hit counter bumped."""
    from kubernetes_tpu import metrics as m

    class CountingFilter(OddNodesOnly):
        calls = 0

        def filter(self, state, pod, node, placed=()):
            CountingFilter.calls += 1
            return super().filter(state, pod, node, placed)

    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [CountingFilter()])
    before_hits = m.fold_cache_total.labels("hit")._value.get()
    cs.create_pod(MakePod().name("a1").req({"cpu": "1"}).obj())
    r1 = sched.schedule_batch()
    calls_after_first = CountingFilter.calls
    assert calls_after_first > 0
    cs.create_pod(MakePod().name("a2").req({"cpu": "1"}).obj())
    r2 = sched.schedule_batch()
    assert CountingFilter.calls == calls_after_first, "fold memo reused"
    assert m.fold_cache_total.labels("hit")._value.get() == before_hits + 1
    for _, node in r1.scheduled + r2.scheduled:
        assert int(node.rsplit("-", 1)[-1]) % 2 == 1


def test_fold_cache_distinguishes_tolerations():
    """Two batches whose reps differ only in a toleration (invisible in
    the in-tree mask on an untainted cluster) must NOT share fold
    verdicts (review-caught under-keyed signature)."""
    class TolerationGate(FilterPlugin):
        def filter(self, state, pod, node, placed=()):
            if any(t.key == "vip" for t in pod.tolerations):
                return Status.success()
            return Status.unschedulable("needs vip toleration")

    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [TolerationGate()])
    cs.create_pod(
        MakePod().name("tolerant").toleration("vip", "true", "NoSchedule")
        .req({"cpu": "1"}).obj()
    )
    r1 = sched.schedule_batch()
    assert len(r1.scheduled) == 1
    cs.create_pod(MakePod().name("plain").req({"cpu": "1"}).obj())
    r2 = sched.schedule_batch()
    assert r2.unschedulable == ["default/plain"], (
        "plain pod must not inherit the tolerant rep's cached verdicts"
    )
