"""Cross-process trace propagation (the fleet-wide observability
tentpole): journey traces minted per pod and stable across retries,
handoff rows carrying the trace between replicas, the hub's journal
aggregation surface, the PR 8 merge rules shared with `obs explain
--fleet`, and the trace context threaded over the extender webhook and
bulk Solve wire boundaries."""

import json

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.fleet import OccupancyExchange
from kubernetes_tpu.obs import (
    FlightRecorder,
    ObsConfig,
    PodDecisionJournal,
    Tracer,
    explain_pod,
    fleet_merge_key,
    merge_fleet_records,
)
from kubernetes_tpu.utils.clock import FakeClock


def _pod(name="p0", ns="default"):
    return MakePod().name(name).namespace(ns).req({"cpu": "100m"}).obj()


class TestJourneyTrace:
    def test_trace_minted_once_and_stable_across_retries(self):
        j = PodDecisionJournal(clock=FakeClock())
        j.origin = "r0-1"
        pod = _pod()
        r1 = j.record(3, 1, pod, "unschedulable")
        r2 = j.record(5, 2, pod, "discarded")
        r3 = j.record(7, 3, pod, "bound", node="n0")
        assert r1["trace"] == "r0-1:3:default/p0"
        assert r1["trace"] == r2["trace"] == r3["trace"]

    def test_bound_retires_the_trace(self):
        j = PodDecisionJournal(clock=FakeClock())
        j.origin = "s-1"
        pod = _pod()
        first = j.record(1, 1, pod, "bound", node="n0")
        # a later migration (evicted_for_rebalance) is a NEW journey
        again = j.record(9, 2, pod, "evicted_for_rebalance", node="n0")
        assert first["trace"] != again["trace"]
        assert again["trace"] == "s-1:9:default/p0"

    def test_non_bound_terminals_keep_the_journey_only_when_retrying(self):
        j = PodDecisionJournal(clock=FakeClock())
        pod = _pod()
        j.record(1, 1, pod, "unschedulable")
        assert pod.key in j.pod_traces  # retries continue this journey
        j.record(2, 2, pod, "quarantined")
        assert pod.key not in j.pod_traces  # TTL re-admit = new history

    def test_seeded_trace_is_reused_verbatim(self):
        """The adopting replica's journal continues the trace the
        handoff row shipped — never re-mints."""
        j = PodDecisionJournal(clock=FakeClock())
        j.origin = "r1-1"
        pod = _pod()
        j.pod_traces[pod.key] = "r0-1:4:default/p0"  # from the claim
        rec = j.record(2, 1, pod, "bound", node="n1")
        assert rec["trace"] == "r0-1:4:default/p0"


class TestHandoffRowTrace:
    def test_hand_off_carries_trace_to_claim(self):
        ex = OccupancyExchange()
        ex.hand_off("r1", "default/p0", 1, from_replica="r0",
                    trace="r0-1:4:default/p0")
        ex.hand_off("r1", "default/a", 2, from_replica="r0")
        claims = ex.claim_handoffs("r1")
        assert claims == [
            ("default/a", 2, ""),
            ("default/p0", 1, "r0-1:4:default/p0"),
        ]
        assert ex.claim_handoffs("r1") == []


class TestHubJournalAggregation:
    def test_ship_and_read_in_arrival_order(self):
        ex = OccupancyExchange()
        ex.ship_journal("r0", ['{"a":1}', '{"a":2}'])
        ex.ship_journal("r1", ['{"b":1}'])
        assert ex.journal_lines() == ['{"a":1}', '{"a":2}', '{"b":1}']

    def test_partitioned_replica_cannot_ship(self):
        from kubernetes_tpu.fleet.occupancy import ExchangeUnreachable

        ex = OccupancyExchange()
        ex.set_partitioned("r0", True)
        with pytest.raises(ExchangeUnreachable):
            ex.ship_journal("r0", ['{"x":1}'])
        assert ex.journal_lines() == []

    def test_fenced_replica_still_ships_journal(self):
        """Journal lines are append-only observability, deliberately
        NOT write-fenced: a zombie's history is what the post-mortem
        needs."""
        ex = OccupancyExchange()
        ex.retire("r0")
        ex.ship_journal("r0", ['{"x":1}'])
        assert ex.journal_lines() == ['{"x":1}']

    def test_runtime_segment_shipping_is_bounded_and_cursor_driven(self):
        from kubernetes_tpu.fleet import FleetConfig
        from kubernetes_tpu.fleet.runtime import FleetRuntime
        from kubernetes_tpu.state.cluster import ClusterState

        clock = FakeClock()
        cs = ClusterState(clock=clock)
        ex = OccupancyExchange(clock=clock)
        rt = FleetRuntime(
            FleetConfig(replica="r0", replicas=("r0",), exchange=ex),
            cs, clock,
        )

        class _Sched:
            journal = PodDecisionJournal(clock=clock)

        sched = _Sched()
        for i in range(5):
            sched.journal.record(1, 1, _pod(f"p{i}"), "bound", node="n0")
        assert rt.ship_journal_segment(sched) == 5
        assert rt.ship_journal_segment(sched) == 0  # cursor advanced
        sched.journal.record(2, 2, _pod("p9"), "bound", node="n0")
        assert rt.ship_journal_segment(sched) == 1
        assert len(ex.journal_lines()) == 6


class TestRemoteJournalBuffer:
    def test_resync_republish_does_not_drop_buffered_journal_lines(self):
        """Review-caught: journal lines ride the write-behind flush but
        in their OWN buffer — replace_pod_rows clears the row buffer
        it supersedes, never the journal history nothing re-creates."""
        from kubernetes_tpu.fleet.runtime import RemoteOccupancyExchange

        sent = []

        class _FakeClient:
            def hub_op(self, op, **meta):
                sent.append((op, meta))
                return {"version": 1, "lines": []}

            def close(self):
                pass

        remote = RemoteOccupancyExchange(
            "unused:0", "r0", client=_FakeClient()
        )
        remote.ship_journal("r0", ['{"a":1}', '{"a":2}'])
        remote.replace_pod_rows("r0", [])  # the resync republish
        assert remote._journal_buffer == ['{"a":1}', '{"a":2}']
        remote.flush()
        ops = next(m["ops"] for op, m in sent if op == "apply_ops")
        assert ops == [["journal", '{"a":1}'], ["journal", '{"a":2}']]

    def test_journal_buffer_bounded_with_counted_drops(self):
        from kubernetes_tpu.fleet.runtime import RemoteOccupancyExchange

        class _DownClient:
            def hub_op(self, op, **meta):
                raise ConnectionError("hub down")

            def close(self):
                pass

        remote = RemoteOccupancyExchange(
            "unused:0", "r0", client=_DownClient()
        )
        remote._JOURNAL_BUFFER_CAP = 4
        from kubernetes_tpu.fleet.occupancy import ExchangeUnreachable

        for i in range(10):
            remote._journal_buffer.append(f'{{"i":{i}}}')
        with pytest.raises(ExchangeUnreachable):
            remote.flush()
        # retained in the sealed batch, oldest beyond the cap dropped
        retained = [
            arg
            for _seq, ops in remote._sealed
            for kind, arg in ops
            if kind == "journal"
        ]
        assert len(retained) == 4  # oldest dropped
        assert remote.journal_lines_dropped == 6
        assert retained[-1] == '{"i":9}'


class TestFleetMerge:
    def test_merge_key_matches_invariant_semantics(self):
        bound = {"t": 2.0, "outcome": "bound", "step": 1}
        failure = {"t": 2.0, "outcome": "bind_failure", "step": 9}
        open_rec = {"t": 2.0, "outcome": "discarded", "step": 9}
        assert fleet_merge_key(bound) > fleet_merge_key(failure)
        assert fleet_merge_key(failure) > fleet_merge_key(open_rec)
        later = {"t": 3.0, "outcome": "discarded", "step": 1}
        assert fleet_merge_key(later) > fleet_merge_key(bound)

    def test_merge_is_permutation_invariant(self):
        recs = [
            {"t": 1.0, "outcome": "unschedulable", "step": 1,
             "replica": "r1", "pod": "default/p"},
            {"t": 2.0, "outcome": "discarded", "step": 2,
             "replica": "r1", "pod": "default/p"},
            {"t": 3.0, "outcome": "bound", "step": 2,
             "replica": "r0", "pod": "default/p"},
        ]
        import itertools

        expect = merge_fleet_records(list(recs))
        for perm in itertools.permutations(recs):
            assert merge_fleet_records(list(perm)) == expect

    def test_fleet_explain_renders_one_chain(self):
        decisions = [
            {"k": "dec", "v": 1, "pod": "default/p", "uid": "", "t": 3.0,
             "step": 2, "cycle": 5, "outcome": "bound", "node": "n2",
             "replica": "r0", "trace": "r1-1:1:default/p"},
            {"k": "dec", "v": 1, "pod": "default/p", "uid": "", "t": 1.0,
             "step": 1, "cycle": 1, "outcome": "unschedulable",
             "replica": "r1", "trace": "r1-1:1:default/p"},
            {"k": "dec", "v": 1, "pod": "default/p", "uid": "", "t": 2.0,
             "step": 2, "cycle": 3, "outcome": "discarded",
             "reason": "handed off to r0: skew", "replica": "r1",
             "trace": "r1-1:1:default/p"},
        ]
        out = explain_pod(decisions, "default/p", fleet=True)
        assert out.replicas == ["r1", "r0"]
        assert out.traces == ["r1-1:1:default/p"]
        assert out.terminal["outcome"] == "bound"
        text = out.render()
        assert "replicas: r1 -> r0" in text
        assert "one journey trace" in text
        assert text.index("[r1] step 1") < text.index("[r0] step 2")


class TestFleetSimEndToEnd:
    def test_handoff_profile_produces_cross_replica_single_trace(self):
        """The acceptance shape: in the fleet sim with handoffs forced,
        a handed-off pod's merged history spans >= 2 replicas, shares
        exactly ONE journey trace, and ends terminally."""
        from kubernetes_tpu.obs.explain import parse_stream
        from kubernetes_tpu.sim.fleet import run_fleet_sim

        res = run_fleet_sim("fleet_handoff", seed=0, cycles=8, replicas=2)
        assert res.ok
        assert res.hub_journal_lines
        decisions, _ = parse_stream(res.hub_journal_lines)
        by_pod: dict[str, set] = {}
        for rec in decisions:
            by_pod.setdefault(rec["pod"], set()).add(rec.get("replica"))
        crossed = [p for p, reps in by_pod.items() if len(reps) > 1]
        assert crossed, "the handoff-forcing profile produced no handoff"
        for pod_key in crossed:
            out = explain_pod(decisions, pod_key, fleet=True)
            assert len(out.replicas) >= 2
            assert len(out.traces) == 1, (
                f"{pod_key}: journey shattered into {out.traces}"
            )

    def test_hub_journal_deterministic_across_runs(self):
        from kubernetes_tpu.sim.fleet import run_fleet_sim

        a = run_fleet_sim("fleet_handoff", seed=3, cycles=6, replicas=2)
        b = run_fleet_sim("fleet_handoff", seed=3, cycles=6, replicas=2)
        assert a.hub_journal_lines == b.hub_journal_lines


class TestWireTraceContext:
    def test_extender_client_attaches_trace_context(self):
        from kubernetes_tpu.config.types import Extender
        from kubernetes_tpu.server.extender_client import HTTPExtenderClient

        seen = []

        def transport(verb, payload):
            seen.append((verb, payload))
            return {"nodenames": ["n0"]}

        cl = HTTPExtenderClient(
            Extender(
                url_prefix="http://x", filter_verb="filter",
                node_cache_capable=True,
            ),
            transport=transport,
        )
        node = MakeNode().name("n0").capacity({"cpu": "1"}).obj()
        cl.filter(_pod(), [node])
        assert "traceContext" not in seen[0][1]  # obs off: bytes unchanged
        cl.trace_context = {"trace": 7, "replica": "r0"}
        cl.filter(_pod(), [node])
        assert seen[1][1]["traceContext"] == {"trace": 7, "replica": "r0"}

    def test_extender_server_span_joins_callers_trace(self):
        from kubernetes_tpu.server.extender import ExtenderCore
        from kubernetes_tpu.state.cluster import ClusterState

        cs = ClusterState()
        cs.create_node(
            MakeNode().name("n0")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
        )
        rec = FlightRecorder()
        tracer = Tracer(clock=FakeClock(), enabled=True, recorder=rec)
        core = ExtenderCore(cs, node_cache_capable=True, tracer=tracer)
        core.filter(
            {
                "pod": _pod().to_dict(),
                "nodenames": ["n0"],
                "traceContext": {"trace": 42, "replica": "r0",
                                 "incarnation": 2},
            }
        )
        batch_spans = [
            s for s in rec.spans() if s["name"] == "extender_batch"
        ]
        assert batch_spans
        sp = batch_spans[-1]
        assert sp["trace"] == 42
        assert sp["attrs"]["replica"] == "r0"
        assert sp["attrs"]["incarnation"] == 2

    def test_bulk_solve_span_joins_callers_trace(self):
        from kubernetes_tpu.server.bulk import BulkClient, BulkCore, SERVICE
        from kubernetes_tpu.server import tensorcodec
        from kubernetes_tpu.state.cluster import ClusterState
        import numpy as np

        cs = ClusterState()
        cs.create_node(
            MakeNode().name("n0")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
        )
        rec = FlightRecorder()
        tracer = Tracer(clock=FakeClock(), enabled=True, recorder=rec)
        core = BulkCore(cs, tracer=tracer)
        payload = tensorcodec.encode(
            {"mode": "exact",
             "trace": {"trace": 9, "parent": 3, "replica": "r1"}},
            {"cpu_milli": np.asarray([100], dtype=np.int64),
             "mem_bytes": np.asarray([1 << 20], dtype=np.int64)},
        )
        core.solve(payload)
        spans = [s for s in rec.spans() if s["name"] == "bulk_solve"]
        assert spans
        assert spans[-1]["trace"] == 9
        assert spans[-1]["attrs"]["replica"] == "r1"
        assert spans[-1]["attrs"]["parent"] == 3
