"""Multi-chip sharding correctness on the 8-device virtual CPU mesh
(SURVEY §6.7; conftest.py provisions the devices).

The node axis is this framework's "sequence/context" dimension: node tables
and carried state shard over it, per-pod inputs replicate, and XLA/GSPMD
inserts the collectives (argmax, cumsum, segment reductions become
cross-shard). These tests prove sharded == unsharded BIT-EQUALITY for both
solvers — the property the driver's dryrun_multichip compile-checks but
cannot assert against a single-chip reference."""

import functools

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from __graft_entry__ import _STATIC_KW, _example_args
from kubernetes_tpu.solver.exact import _solve_scan
from kubernetes_tpu.solver.single_shot import SingleShotConfig, SingleShotSolver
from kubernetes_tpu.tensorize.schema import build_node_batch, build_pod_batch
from kubernetes_tpu.api.wrappers import MakeNode, MakePod

N_DEVICES = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEVICES:
        pytest.skip(f"needs {N_DEVICES} virtual devices")
    return Mesh(np.array(jax.devices()[:N_DEVICES]), axis_names=("nodes",))


def _shardings(mesh, tables, state0, xs):
    shard_2d = NamedSharding(mesh, P(None, "nodes"))
    shard_1d = NamedSharding(mesh, P("nodes"))
    repl = NamedSharding(mesh, P())

    def node_sharding(a):
        if a.ndim == 2:
            return shard_2d
        return shard_1d

    tables_sh = jtu.tree_map(node_sharding, tables)
    # per-instance/per-class scalar tables are replicated (no node axis)
    for grp, names in (
        ("spr", ("max_skew", "min_domains", "self_match", "is_hostname", "hard", "soft")),
        ("ipa", ("in_pref_w", "cls_req_aff", "cls_req_anti", "cls_pref", "ex_anti")),
    ):
        for name in names:
            tables_sh[grp][name] = repl
    state_sh = jtu.tree_map(node_sharding, state0)
    xs_sh = jtu.tree_map(lambda a: repl, xs)
    return tables_sh, state_sh, xs_sh, repl


def test_exact_scan_sharded_equals_unsharded(mesh):
    """The full exact-parity scan (spread + interpod active) over a 1024-node
    axis sharded 8 ways must produce the identical assignment sequence and
    final node state."""
    tables, state0, xs = _example_args(n_nodes=1024, n_pods=64)
    fn = functools.partial(_solve_scan, **_STATIC_KW, fdtype=jnp.float32)
    key = jax.random.PRNGKey(0)

    ref_asg, ref_state = jax.jit(fn)(tables, state0, xs, key)
    ref_asg = np.asarray(ref_asg)

    tables_sh, state_sh, xs_sh, repl = _shardings(mesh, tables, state0, xs)
    out = jax.jit(fn, in_shardings=(tables_sh, state_sh, xs_sh, repl))(
        jtu.tree_map(jax.device_put, tables, tables_sh),
        jtu.tree_map(jax.device_put, state0, state_sh),
        jtu.tree_map(jax.device_put, xs, xs_sh),
        jax.device_put(key, repl),
    )
    np.testing.assert_array_equal(np.asarray(out[0]), ref_asg)
    for k in ref_state:
        np.testing.assert_array_equal(
            np.asarray(out[1][k]), np.asarray(ref_state[k]), err_msg=k
        )
    assert int((ref_asg >= 0).sum()) == 64  # everything placed


def _single_shot_workload(n_nodes=1024, n_pods=768):
    rng = np.random.default_rng(42)
    nodes = [
        MakeNode()
        .name(f"n-{i:04}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "40"})
        .obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        cpu = int(rng.integers(1, 8)) * 250
        mem = int(rng.integers(1, 5)) << 29
        pods.append(
            MakePod()
            .name(f"p-{i:04}")
            .req({"cpu": f"{cpu}m", "memory": mem})
            .priority(int(rng.integers(0, 5)))
            .obj()
        )
    batch = build_node_batch(nodes)
    pbatch = build_pod_batch(pods, batch.vocab)
    return batch, pbatch


def test_parallel_sharding_helpers(mesh):
    """parallel/sharding.py: the mesh/spec helpers used by the solvers."""
    from kubernetes_tpu.parallel.sharding import (
        device_put_tree,
        node_mesh,
        node_sharding,
        replicated,
        shard_node_tree,
    )

    m = node_mesh(N_DEVICES)
    assert m.axis_names == ("nodes",)
    s2 = node_sharding(m, 2)
    assert s2.spec == (None, "nodes")
    s1 = node_sharding(m, 1)
    assert s1.spec == ("nodes",)
    assert replicated(m).spec == ()

    tree = {
        "alloc": np.zeros((3, 1024), np.int64),
        "max_skew": np.ones(8, np.int32),
    }
    sh = shard_node_tree(m, tree, replicate_names=frozenset({"max_skew"}))
    assert sh["alloc"].spec == (None, "nodes")
    assert sh["max_skew"].spec == ()
    placed = device_put_tree(tree, sh)
    np.testing.assert_array_equal(np.asarray(placed["alloc"]), tree["alloc"])


ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def _shape_pod(i: int, kind: str):
    b = MakePod().name(f"{kind}{i:03}").req(
        {"cpu": "100m", "memory": "256Mi"}
    )
    if kind == "spread":
        b = b.label("app", "spread").spread_constraint(
            1, ZONE, "DoNotSchedule", {"app": "spread"}
        )
    elif kind == "anti":
        b = b.label("app", "anti").pod_anti_affinity(HOST, {"app": "anti"})
    elif kind == "ports":
        b = b.host_port(8000 + i % 3)
    return b.obj()


def _mk_cluster(n_nodes=6):
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .label(HOST, f"n{i}")
            .obj()
        )
    return cs


def _mk_sched(cs, mesh_devices, **cfg):
    from kubernetes_tpu.obs import ObsConfig
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig

    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16,
            mesh_devices=mesh_devices,
            solver=ExactSolverConfig(tie_break="first", group_size=8),
            obs=ObsConfig(journal=True),
            **cfg,
        ),
    )


def _exact_standalone(kind, mesh, n_nodes=512, n_pods=48):
    """One standalone ExactSolver.solve over the production tensorizers
    for a hard shape; returns (assignments, NodeBatch) for comparison."""
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
    from kubernetes_tpu.tensorize.plugins import (
        build_port_tensors,
        build_static_tensors,
    )
    from kubernetes_tpu.tensorize.spread import build_spread_tensors

    nodes = [
        MakeNode()
        .name(f"n-{i:04}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "40"})
        .label(ZONE, f"z{i % 3}")
        .label(HOST, f"n-{i:04}")
        .obj()
        for i in range(n_nodes)
    ]
    pods = [_shape_pod(i, kind) for i in range(n_pods)]
    from kubernetes_tpu.tensorize.schema import pad_to

    npad = pad_to(n_nodes)  # LANE multiple => divisible by the 8-way mesh
    batch = build_node_batch(nodes, pad=npad)
    pbatch = build_pod_batch(pods, batch.vocab)
    slots = list(nodes) + [None] * (npad - n_nodes)
    static = build_static_tensors(pods, pbatch, slots, npad)
    ports = build_port_tensors(pods, pbatch, slots, {}, npad)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slots, {}, npad, static.c_pad
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slots, {}, npad, static.c_pad
    )
    solver = ExactSolver(ExactSolverConfig(tie_break="first", group_size=16))
    asg = solver.solve(
        batch, pbatch, static, ports, spread, interpod, mesh=mesh
    )
    return np.asarray(asg), batch


@pytest.mark.parametrize("kind", ["plain", "ports", "spread", "anti"])
def test_exact_solver_sharded_equals_unsharded(mesh, kind):
    """The PRODUCTION exact path — ExactSolver.solve through the real
    tensorizers — sharded 8 ways over the node axis must produce the
    bit-identical assignment vector AND final node state (the objective:
    identical used/pod_count columns) for every hard shape."""
    sharded, batch_sh = _exact_standalone(kind, mesh)
    ref, batch_ref = _exact_standalone(kind, None)
    np.testing.assert_array_equal(sharded, ref, err_msg=kind)
    np.testing.assert_array_equal(batch_sh.used, batch_ref.used)
    np.testing.assert_array_equal(batch_sh.pod_count, batch_ref.pod_count)
    assert int((sharded >= 0).sum()) > 0


@pytest.mark.parametrize("kind", ["plain", "ports", "spread", "anti"])
def test_scheduler_mesh_end_to_end_equivalence(mesh, kind):
    """End to end through the Scheduler (session mode, dirty-column
    heals, the pipelined carry/overlap modes): mesh_devices=8 must bind
    the same pods to the same nodes as the single-device path, with the
    same per-pod journal outcomes."""

    def drive(mesh_devices):
        cs = _mk_cluster()
        s = _mk_sched(cs, mesh_devices)
        for i in range(20):
            cs.create_pod(_shape_pod(i, kind))
        s.run_pipelined()
        bindings = sorted((p.name, p.node_name) for p in cs.list_pods())
        outcomes = {
            pod: (rec.get("outcome"), rec.get("node"))
            for pod, rec in s.journal.last_outcomes().items()
        }
        return bindings, outcomes

    b8, o8 = drive(8)
    b1, o1 = drive(1)
    assert b8 == b1, kind
    assert o8 == o1, kind
    assert any(n for _, n in b8)  # something actually bound


def test_padding_rows_never_bound(mesh):
    """Padded node columns (node count not divisible by the device
    count) must stay masked out of every filter/score/argmax/occupancy
    path: under delete churn with 5 live nodes on an 8-way mesh, no pod
    may ever bind to a padding slot (which would surface as a binding to
    a node name that does not exist)."""
    cs = _mk_cluster(n_nodes=5)  # 5 % 8 != 0; snapshot pads to 128
    s = _mk_sched(cs, 8)
    for i in range(12):
        cs.create_pod(_shape_pod(i, "spread"))
    s.run_pipelined()
    live = {f"n{i}" for i in range(5)}
    # churn: delete a node (its column becomes a padding-like invalid
    # slot) and keep scheduling
    victims = [p for p in cs.list_pods() if p.node_name == "n4"]
    for p in victims:
        cs.delete_pod(p.namespace, p.name)
    cs.delete_node("n4")
    live.discard("n4")
    for i in range(12, 20):
        cs.create_pod(_shape_pod(i, "anti"))
    s.run_pipelined()
    for p in cs.list_pods():
        if p.node_name:
            assert p.node_name in live, (p.name, p.node_name)
    # direct solver-level guard: assignments never reference a padded or
    # invalid slot
    asg, batch = _exact_standalone("plain", mesh, n_nodes=5, n_pods=8)
    assert int(asg.max()) < 5
    assert int((asg >= 0).sum()) == 8


def test_pad_multiple_at_100k_nodes_mesh8(mesh):
    """Snapshot.pad_multiple at 10x-proven-scale node counts (ISSUE 12
    satellite): with >= 100k NON-multiple node counts under the virtual
    mesh-8, the padding honors lcm(LANE, devices), padded columns stay
    masked (valid=False/schedulable=False) across delete churn, and a
    real sharded session solve never binds a padding row. Property-
    swept over several awkward counts host-side (the cheap part); the
    solve runs once at the largest."""
    import math

    from kubernetes_tpu.api.objects import Node
    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.snapshot import Snapshot
    from kubernetes_tpu.tensorize.schema import LANE

    q = math.lcm(LANE, N_DEVICES)

    def build(n):
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(
                Node(
                    name=f"n{i:06}",
                    allocatable={
                        "cpu": 16_000, "memory": 64 << 30, "pods": 110
                    },
                )
            )
        snap = Snapshot()
        snap.pad_multiple = N_DEVICES
        return cache, snap, snap.update(cache)

    def check_padding(b, n_live, fresh=True):
        assert b.padded % q == 0 and b.padded >= n_live
        assert int(b.valid.sum()) == n_live
        assert int(b.schedulable.sum()) == n_live
        # every non-live column is masked out of filter/score/argmax
        pad = ~b.valid
        assert not b.schedulable[pad].any()
        if fresh:
            # never-written padding columns also hold impossible values
            # (churn-freed slots keep stale numbers by design — the
            # valid/schedulable mask is the guard, asserted above)
            assert int(b.allocatable[:, pad].sum()) == 0

    # host-side property sweep: awkward non-multiple counts >= 100k
    # (prime-ish, q-1, q+1 offsets) all honor the discipline
    for n in (100_003, 100_608 - 1, 100_608 + 1, 102_400 + 7):
        _, _, b = build(n)
        check_padding(b, n)

    # full path at the largest count: delete churn, then a SHARDED
    # session solve — no binding may reference a padding/invalid slot
    n = 102_407
    cache, snap, b = build(n)
    for i in range(0, 512, 2):
        cache.remove_node(f"n{i:06}")
    b = snap.update(cache)
    check_padding(b, n - 256, fresh=False)
    pb = columnar_pod_batch(
        np.full(16, 250, np.int64),
        np.full(16, 512 << 20, np.int64),
        None,
        b.vocab,
    )
    solver = ExactSolver(
        ExactSolverConfig(tie_break="first", group_size=16)
    )
    asg = solver.solve(
        b, pb, col_versions=snap.col_versions, mesh=mesh
    )
    assert int((asg >= 0).sum()) == 16
    for slot in np.asarray(asg):
        assert b.valid[slot], f"bound to padding/invalid slot {slot}"


def test_sim_trace_device_count_invariant(mesh):
    """Same seed, same profile, different device count => byte-identical
    trace AND decision journal (the bit-exact invariance contract,
    proven end to end through the simulator's churn/fault machinery)."""
    from kubernetes_tpu.sim.harness import run_sim

    r1 = run_sim("churn_heavy", seed=0, cycles=3, mesh_devices=1)
    r8 = run_sim("churn_heavy", seed=0, cycles=3, mesh_devices=8)
    assert r1.ok and r8.ok
    assert r1.journal_lines == r8.journal_lines
    assert r1.trace.lines == r8.trace.lines


def test_single_shot_sharded_equals_unsharded(mesh):
    """The auction solver — the 50k x 10k rebalance engine, i.e. the actual
    v5e-8 workload — sharded over the node axis must commit the identical
    assignment vector and node state."""
    batch_ref, pbatch = _single_shot_workload()
    batch_sh, _ = _single_shot_workload()

    solver = SingleShotSolver(SingleShotConfig())
    ref = solver.solve(batch_ref, pbatch)
    sharded = solver.solve(batch_sh, pbatch, mesh=mesh)

    np.testing.assert_array_equal(sharded, ref)
    np.testing.assert_array_equal(batch_sh.used, batch_ref.used)
    np.testing.assert_array_equal(batch_sh.pod_count, batch_ref.pod_count)
    placed = int((ref >= 0).sum())
    assert placed == pbatch.num_pods  # capacity is ample: all place
