"""SimHarness: drive the REAL Scheduler through the REAL ClusterState
under generated churn and injected faults, on virtual time, checking
invariants after every drive — the regression harness the pipelined
loop's concurrency story is validated against.

One harness = one deterministic run:

    seed + profile  ──►  churn events (gen RNG)  ─┐
                    ──►  fault decisions (fault RNG, journaled)  ─┤
                                                                  ▼
    FakeClock ── ClusterState ── DelayedWatchBus ── Scheduler.run_pipelined
                     ▲                                    │ post-dispatch hook
                     └── BindTransitionTracker (ground truth watch)

Everything that could vary between runs is pinned: a single-threaded
event loop, ``FakeClock`` virtual time threaded through scheduler /
queue / cache / cluster, ``tie_break="first"`` solves, sorted iteration
in generators/checkers, and RNG streams seeded from strings (immune to
PYTHONHASHSEED). Two runs with the same seed+profile produce
byte-identical traces; ``replay`` re-executes a recorded trace's events
and fault decisions literally and diffs the final bindings against its
footer.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from .. import metrics
from ..config.types import Extender
from ..scheduler import Scheduler, SchedulerConfig
from ..server.extender_client import ExtenderError
from ..solver.exact import ExactSolverConfig
from ..state.cluster import ClusterState
from ..utils.clock import FakeClock
from .faults import (
    BindFaultInjector,
    CrashInjector,
    DecisionJournal,
    DelayedWatchBus,
    FlakyExtenderTransport,
    SimulatedCrash,
    SolverFaultInjector,
    StallingPermitPlugin,
)
from .generators import ChurnGenerator, apply_event
from ..obs import ObsConfig
from .invariants import (
    BindTransitionTracker,
    MonotonicCounters,
    RebalanceTracker,
    Violation,
    _record,
    check_capacity,
    check_constraints,
    check_journal_completeness,
    check_lost_pods,
    check_megaplan,
    check_no_partial_gangs,
    check_rebalance,
    check_recovery,
    check_resilience,
    check_telemetry,
    check_tuning,
    merged_last_outcomes,
    packed_utilization,
)
from .profiles import Profile, get_profile
from .trace import TraceReader, TraceWriter


@dataclass
class SimResult:
    profile: str
    seed: int
    cycles: int
    bindings: dict[str, str]  # pod key -> node (final, bound pods only)
    unbound: list[str]  # pod keys still pending at the end
    violations: list[Violation]
    settled: bool
    summary: dict
    trace: TraceWriter
    replay_divergence: str | None = None  # replay mode only
    # per-pod decision journal (kubernetes_tpu/obs), canonical JSONL:
    # same seed+profile => byte-identical lines
    journal_lines: list[str] = None
    flight_dump: str | None = None  # written on invariant violation
    # --tuning runs: the converged knobs as a standard
    # KubeSchedulerConfiguration document (tuning/profile.py)
    tuned_profile: dict | None = None

    @property
    def ok(self) -> bool:
        return (
            not self.violations
            and self.settled
            and self.replay_divergence is None
        )


# counters whose within-run deltas go into the footer summary (reading
# absolutes would leak cross-run registry state into the trace)
_DELTA_COUNTERS = {
    "discards": metrics.solves_discarded_total,
    "pipeline_fallbacks": metrics.pipeline_fallback_total,
    "preemptions": metrics.preemption_attempts_total,
    # streaming dispatcher: slots killed by per-slot fence epochs —
    # driver-thread logic, so same-seed runs stay byte-identical
    "stream_discards": metrics.stream_slot_discard_total,
}


def _counter_value(c) -> float:
    return c._value.get()  # prometheus_client internal, test-style read


# gang footer block (gang profiles): within-run deltas of the gang
# counters. Deltas of GLOBAL metrics rather than scheduler-object
# state, so the numbers survive crash_restart incarnation swaps.
_GANG_COUNTERS = {
    "gang_commits": metrics.gang_commits_total,
    "gang_bound_pods": metrics.gang_bound_pods_total,
    "gang_incomplete_rounds": metrics.gang_incomplete_total,
    "quarantined_gangs": metrics.gang_quarantined_total,
}


def _gang_throughput_table(profile: Profile) -> dict:
    """Deterministic workload-class x accelerator-class effective-
    throughput table derived from the profile's class lists alone (no
    RNG: same profile => same table => byte-identical solves). Rows are
    rotations of a fixed ladder, so every workload class prefers a
    different accelerator class — real placement pressure for the
    heterogeneity term to resolve."""
    ladder = (1.0, 0.75, 0.5, 0.25)
    return {
        wc: {
            ac: ladder[(i + j) % len(ladder)]
            for j, ac in enumerate(profile.gang_accel_classes)
        }
        for i, wc in enumerate(profile.gang_workload_classes)
    }


class SimHarness:
    def __init__(
        self,
        profile: Profile | str,
        seed: int = 0,
        cycles: int = 10,
        *,
        pipelined: bool | None = None,
        streaming: bool | None = None,
        replay: TraceReader | None = None,
        max_settle_rounds: int = 12,
        spans: bool = False,
        flight_dump: str | None = None,
        mesh_devices: int = 1,
        tuning: bool | None = None,
        bundle_dir: str | None = None,
    ) -> None:
        self.profile = (
            get_profile(profile) if isinstance(profile, str) else profile
        )
        self.profile.validate()
        self.seed = seed
        self.cycles = cycles
        self.pipelined = (
            self.profile.pipelined if pipelined is None else pipelined
        )
        # streaming dispatcher drive (Scheduler.run_streaming): profile
        # default, overridable per run (the CI smokes re-drive the
        # chaos/crash profiles through it)
        self.streaming = (
            self.profile.streaming if streaming is None else streaming
        )
        # closed-loop auto-tuning (kubernetes_tpu/tuning): profile
        # default, overridable per run (the --tuning CLI flag enables
        # the runtime on ANY profile)
        self.tuning = self.profile.tuning if tuning is None else tuning
        self.max_settle_rounds = max_settle_rounds
        self._reader = replay

        self.trace = TraceWriter()
        self.trace.header(
            seed=seed,
            profile=self.profile.name,
            cycles=cycles,
            pipelined=self.pipelined,
            streaming=self.streaming,
            tuning=self.tuning,
        )
        self.journal = DecisionJournal(
            None if replay is not None else self.trace,
            replay.decisions if replay is not None else None,
        )
        # two independent RNG streams (string-seeded: hash-seed immune):
        # churn generation consumes gen, injectors consume fault — so
        # mid-run fault draws never shift what churn a cycle produces
        self._gen_rng = random.Random(f"{seed}/gen")
        self._fault_rng = random.Random(f"{seed}/fault")

        self.clock = FakeClock()
        self.cluster = ClusterState(clock=self.clock)
        self.generator = ChurnGenerator(
            self.profile, self._gen_rng, self.cluster
        )
        for node in self.generator.seed_nodes():
            self.cluster.create_node(node)

        plugins: tuple = ()
        self.permit_plugin: StallingPermitPlugin | None = None
        if self.profile.permit:
            self.permit_plugin = StallingPermitPlugin(
                self.journal,
                self._fault_rng,
                self.profile.permit_stall_rate,
                self.profile.permit_timeout,
            )
            plugins = (self.permit_plugin,)
        extenders: tuple = ()
        if self.profile.extender:
            extenders = (
                Extender(
                    url_prefix="http://sim-extender",
                    filter_verb="filter",
                    prioritize_verb="prioritize",
                    node_cache_capable=True,
                ),
            )
        self.flight_dump_path = flight_dump
        # capture-on-anomaly replay bundles (telemetry profiles): the
        # telemetry invariant replays every bundle written here
        self.bundle_dir = bundle_dir
        # continuous rebalancer (kubernetes_tpu/rebalance): the
        # fragmentation profile's defragmentation loop, plus a seeded
        # PDB-guarded cohort the rebalancer must never move
        rebalance_cfg = None
        self.rebalance_tracker: RebalanceTracker | None = None
        if self.profile.rebalance:
            from ..rebalance.runtime import RebalanceConfig

            rebalance_cfg = RebalanceConfig(
                interval_s=self.profile.rebalance_interval_s,
                max_moves_per_cycle=self.profile.rebalance_budget,
                min_packing=self.profile.rebalance_min_packing,
            )
            if self.profile.pdb_guard_rate > 0:
                from ..api.labels import (
                    Selector,
                    requirements_from_match_labels,
                )
                from ..api.objects import PodDisruptionBudget
                from .generators import PDB_GUARD_LABEL

                self.cluster.create_pdb(
                    PodDisruptionBudget(
                        name="sim-pdb-guard",
                        namespace="default",
                        selector=Selector(
                            requirements=requirements_from_match_labels(
                                {PDB_GUARD_LABEL: "1"}
                            )
                        ),
                        disruptions_allowed=0,
                    )
                )
            # constructed AFTER the PDB so its allowance mirror seeds
            # from the original budgets
            self.rebalance_tracker = RebalanceTracker(self.cluster)
        from ..resilience import ResilienceConfig

        # sim-sized tuning windows: short enough that both directions
        # of every knob are probed AND settled within a run's batch
        # budget (the production defaults evaluate over longer
        # windows). Hysteresis is WIDE (50%): on virtual time a knob
        # cannot genuinely change throughput — the measured objective
        # is pure arrival noise — so the correct converged behavior is
        # "no direction improves, stay put and settle", and a
        # production-sized 5% margin would let that noise random-walk
        # the knobs forever instead.
        tuning_cfg = None
        if self.tuning:
            from ..tuning.runtime import TuningConfig

            tuning_cfg = TuningConfig(
                eval_batches=2, settle_after=1, hysteresis=0.5,
                # the tuning_convergence shift is a 1.5x rate change;
                # 0.7 clears the within-regime arrival noise (uniform
                # bands over a 4-sample window swing ~±0.4 relative)
                # while detecting the real shift with margin
                shift_threshold=0.7, max_probes=4,
            )
        gang_cfg = None
        self._gang_profile = (
            self.profile.gang_rate > 0 or self.profile.gang_short_at >= 0
        )
        resilience_kwargs: dict = {
            "open_seconds": self.profile.resilience_open_s
        }
        if self._gang_profile:
            from ..gang import GangConfig

            gang_cfg = GangConfig(
                min_member_timeout=self.profile.gang_min_member_timeout,
                quarantine_after=self.profile.gang_quarantine_after,
                throughput_weight=self.profile.gang_throughput_weight,
                class_throughput=_gang_throughput_table(self.profile),
            )
            # park the quarantined gang PAST the settle horizon: a TTL
            # re-admit landing in the settle tail would re-park the
            # gang `gang_incomplete` (non-terminal) with no waking
            # event left to drive it back to quarantine, misreading
            # "terminally quarantined" as "dropped" in the journal-
            # completeness invariant. The re-admit cycle itself is
            # unit-tested (tests/test_gang.py), not sim-driven.
            resilience_kwargs["quarantine_ttl"] = 3600.0
        self._base_config = SchedulerConfig(
            batch_size=self.profile.batch_size,
            # short breaker fault window so probes and re-closes
            # land inside the run's virtual timeline (the
            # resilience invariant asserts the re-close)
            resilience=ResilienceConfig(**resilience_kwargs),
            # gang scheduling (gang profiles): pod groups admitted,
            # queued, and bound atomically, with the heterogeneity
            # throughput table derived deterministically from the
            # profile's class lists
            gang=gang_cfg,
            # node-axis solve mesh: results are bit-exactly device-
            # count invariant, so a mesh_devices=N run's trace and
            # journal must be byte-identical to the single-device run
            # with the same seed (the multichip CI smoke leans on
            # this). Default 1: sim runs are usually single-device.
            mesh_devices=mesh_devices,
            solver=ExactSolverConfig(
                tie_break="first", group_size=self.profile.group_size
            ),
            extenders=extenders,
            out_of_tree_plugins=plugins,
            rebalance=rebalance_cfg,
            tuning=tuning_cfg,
            # every sim scheduler binds under a fence token so a
            # crash-restarted incarnation structurally supersedes its
            # predecessor (the commit-fencing layer rides every
            # profile; it only acts when a token goes stale)
            fence_role="sim-scheduler",
            # the decision journal is always on in the sim: the
            # trace-completeness invariant and the byte-identical-
            # journal determinism contract both ride on it. Spans
            # are opt-in (they multiply recorder traffic).
            obs=self._build_obs_config(spans, flight_dump, bundle_dir),
        )
        # process lifecycle (crash_restart): incarnations share one
        # virtual timeline; a crash retires the live scheduler's
        # journal here and a fresh incarnation takes over
        self.incarnations = 1
        self._dead_journals: list[list[str]] = []
        self._orphans_at_restart = 0
        self.crash_injector: CrashInjector | None = None
        if self.profile.crash_at >= 0:
            self.crash_injector = CrashInjector()
        self.scheduler = Scheduler(
            self.cluster, self._base_config, clock=self.clock
        )
        if self.crash_injector is not None:
            self.scheduler._pre_commit_hook = self.crash_injector
        self.ext_transport: FlakyExtenderTransport | None = None
        if self.profile.extender:
            self.ext_transport = FlakyExtenderTransport(
                self.journal, self._fault_rng, self.profile.extender_fault_rate
            )
            for cl in self.scheduler.extender_clients:
                cl.transport = self.ext_transport

        # interpose the delayed bus between cluster and scheduler; the
        # ground-truth tracker subscribes directly (no delay)
        self.cluster.unsubscribe(self.scheduler._on_event)
        self.bus = DelayedWatchBus(
            self.cluster,
            self.scheduler._on_event,
            self.journal,
            self._fault_rng,
            delaying=self.profile.watch_delay,
            dup_rate=self.profile.watch_dup_rate,
        )
        self.cluster.subscribe(self.bus.ingest)
        self.scheduler._post_dispatch_hook = self._on_dispatch

        self.bind_injector = BindFaultInjector(
            self.journal, self._fault_rng, self.profile.bind_fault_rate
        )
        self.cluster.bind_fault = self.bind_injector

        # solver-boundary faults (the one boundary below schedule_batch):
        # installed on the scheduler's _solve_fault seam, called before
        # every solve attempt at every fallback-ladder tier
        self.solver_injector: SolverFaultInjector | None = None
        if self.profile.solver_fault_rate > 0 or self.profile.poison_rate > 0:
            self.solver_injector = SolverFaultInjector(
                self.journal,
                self._fault_rng,
                self.clock,
                rate=self.profile.solver_fault_rate,
                window=self.profile.solver_fault_window,
            )
            self.scheduler._solve_fault = self.solver_injector

        self.tracker = BindTransitionTracker(self.cluster)
        self.monotonic = MonotonicCounters()
        self.violations: list[Violation] = []
        # binds THIS scheduler reported (vs external churn binds): the
        # journal-completeness invariant holds exactly these to a
        # terminal 'bound' record
        self._sched_bound: set[str] = set()
        self._events_applied = 0
        self._extender_aborts = 0
        # backlog drain (backlog_drain profiles): cycle 0's
        # drain_backlog report, surfaced in the footer summary
        self._backlog_report = None
        # mega-planner probe result (megaplan profiles, ISSUE 19):
        # relax-vs-oracle A/B on the pre-drain snapshot, counts and
        # rounded ratios only so --selfcheck stays byte-identical
        self._megaplan = None
        # was the tuner settled when the profile's workload shift
        # landed? Shift detection compares against the SETTLED
        # baseline signature, so a tuner still mid-convergence at the
        # shift structurally cannot detect it — the invariant's
        # shift-detected clause is only fair when this is True
        self._tuner_settled_at_shift = False
        self._counters0 = {
            k: _counter_value(c) for k, c in _DELTA_COUNTERS.items()
        }
        self._gang_counters0 = {
            k: _counter_value(c) for k, c in _GANG_COUNTERS.items()
        }

    def _build_obs_config(
        self,
        spans: bool,
        flight_dump: str | None,
        bundle_dir: str | None,
    ) -> ObsConfig:
        """The sim's ObsConfig: journal always on; flight telemetry
        (profiler + sentinel + capture) only on ``profile.telemetry``
        profiles, with sim-sized sentinel windows so a 12-cycle run has
        enough window samples for both spike and drift rules. All
        telemetry arithmetic rides the FakeClock, so same-seed runs
        stay byte-identical through the footer summary."""
        kwargs: dict = {
            "spans": spans, "journal": True, "dump_path": flight_dump
        }
        if self.profile.telemetry:
            from ..obs import SentinelConfig
            from ..obs.slo import SloConfig

            kwargs.update(
                profile=True,
                # a sync-drive cycle applies ~1 batch, so windows close
                # every 2 batches and the spike rule (1 fast vs 3 slow,
                # single-window hysteresis) can fire within the storm's
                # 3-cycle fault window. min_events=1: sim event volumes
                # are tiny.
                sentinel=SentinelConfig(
                    window_batches=2,
                    fast_windows=1,
                    slow_windows=3,
                    spike_ratio=2.0,
                    drift_ratio=1.5,
                    hysteresis=1,
                    cooldown_windows=4,
                    min_windows=3,
                    min_events=1.0,
                    recover_windows=2,
                ),
                # the sentinel's p99 source; export_interval_s=0 keeps
                # quantiles fresh every observe on the virtual clock
                slo=SloConfig(export_interval_s=0.0),
                bundle_dir=bundle_dir,
            )
        return ObsConfig(**kwargs)

    # -- fault delivery inside the dispatch→apply window --

    def _on_dispatch(self, flight) -> None:
        """Post-dispatch hook: while a solve is in flight (the one real
        window where another actor's events race a deferred solve),
        deliver some delayed watch events — this is what makes fence
        discards, session re-uploads, and the livelock backstop
        reachable from a single-threaded simulation."""
        if not self.bus.delaying or not self.bus.pending:
            return
        pending = len(self.bus.pending)

        def draw():
            if self._fault_rng.random() < 0.2:
                return 0
            return min(pending, 1 + self._fault_rng.randrange(2))

        self.bus.pump(self.journal.decide("midpump", draw))

    # -- drive + invariants --

    def _drive(self, cycle: int) -> None:
        try:
            self._drive_once(cycle)
        except SimulatedCrash:
            # the scheduler process died mid-batch (after assume,
            # before bind): every piece of incarnation-local state —
            # assumed pods, Permit waiters, in-flight maps, deferred
            # solves — evaporates with the object, and a fresh
            # incarnation recovers from cluster truth. Batches the
            # dying drive had already completed lose their result
            # accounting (acceptable: the ground-truth tracker still
            # watches the state service directly).
            self._restart(cycle)

    def _restart(self, cycle: int) -> None:
        """Construct the successor incarnation on the same ClusterState
        and re-wire the harness seams to it. The dead incarnation's
        journal is retained — the completeness invariant merges it with
        its successors' (its dangling non-terminal histories must be
        closed by the recovery pass's terminal ``recovered``
        records)."""
        import dataclasses

        dead = self.scheduler
        self._dead_journals.append(list(dead.journal.lines))
        self.incarnations += 1
        self._orphans_at_restart = sum(
            1 for p in self.cluster.list_pods() if not p.node_name
        )
        cfg = dataclasses.replace(
            self._base_config, incarnation=self.incarnations
        )
        new = Scheduler(self.cluster, cfg, clock=self.clock)
        # mirror the init wiring: the new incarnation's watch stream
        # routes through the (shared) delivery bus, not directly
        self.cluster.unsubscribe(new._on_event)
        self.bus._deliver = new._on_event
        new._post_dispatch_hook = self._on_dispatch
        if self.crash_injector is not None:
            new._pre_commit_hook = self.crash_injector
        if self.solver_injector is not None:
            new._solve_fault = self.solver_injector
        if self.ext_transport is not None:
            for cl in new.extender_clients:
                cl.transport = self.ext_transport
        self.scheduler = new
        # bounded recovery: the fresh incarnation must account for
        # EVERY unbound pod the moment its recovery pass finishes —
        # before any drive — or the crash lost work
        check_lost_pods(
            self.cluster, new, cycle, self.violations,
            undelivered=self.bus.pending_pod_adds,
        )

    def _megaplan_probe(self) -> None:
        """Mega-planner acceptance probe (megaplan profiles, ISSUE 19):
        on the FROZEN pre-drain cycle-0 snapshot, solve the whole
        backlog with the convex relaxation (dual ascent + deterministic
        rounding + auction tail repair — the exact engine the planner
        and warm-start use) and replay the plan against the sequential
        oracle:

        - **validity** — every placed pick must be in the oracle's
          feasible set at that step (``FullOracle.validate_feasible``:
          no overcommit, every filter honored — tie-set parity is
          deliberately NOT required of a global plan);
        - **quality** — placements vs the oracle's own greedy run on
          the identical snapshot; check_megaplan asserts the ratio
          floor.

        Everything is host python over frozen arrays — counts and
        rounded ratios only ride the footer, so same-seed runs stay
        byte-identical under --selfcheck."""
        import dataclasses

        from ..ops.oracle.profile import FullOracle, make_oracle_nodes
        from ..solver.relax import RelaxConfig, RelaxSolver
        from ..solver.single_shot import SingleShotConfig
        from ..tensorize.plugins import build_static_tensors
        from ..tensorize.schema import build_pod_batch

        sched = self.scheduler
        with self.cluster.lock:
            batch = sched.snapshot.update(sched.cache)
            pods = sched.queue.active_pods()
            slot_nodes = []
            for name in sched.snapshot.names:
                info = sched.cache.nodes.get(name) if name else None
                slot_nodes.append(info.node if info is not None else None)
            bound: dict[str, list] = {}
            for p in self.cluster.list_pods():
                if p.node_name:
                    bound.setdefault(p.node_name, []).append(p)
        if not pods or batch.num_nodes == 0:
            return
        # the queue's own pop order: priority bands first, FIFO within
        pods = sorted(
            pods, key=lambda p: (-p.effective_priority, p.key)
        )
        pbatch = build_pod_batch(pods, batch.vocab)
        static = build_static_tensors(
            pods, pbatch, slot_nodes, batch.padded
        )
        plan_batch = dataclasses.replace(
            batch,
            allocatable=batch.allocatable.copy(),
            used=batch.used.copy(),
            nonzero_used=batch.used[:2].copy(),
            pod_count=batch.pod_count.copy(),
        )
        solver = RelaxSolver(RelaxConfig(), repair=SingleShotConfig())
        assigned = solver.solve(plan_batch, pbatch, static)
        stats = solver.last
        picks = [int(a) for a in assigned]
        # a pick into the padding region is a validity failure in its
        # own right — mask it to unplaced for the replay, count it
        oob = [
            (p.key, a)
            for p, a in zip(pods, picks)
            if a >= batch.num_nodes
        ]
        picks = [a if a < batch.num_nodes else -1 for a in picks]
        names = [
            batch.names[a] if a >= 0 else None for a in picks
        ]
        real_nodes = [nd for nd in slot_nodes if nd is not None]
        errors = [
            f"pod {k}: pick {a} is a padding slot" for k, a in oob
        ] + FullOracle(
            make_oracle_nodes(real_nodes, bound)
        ).validate_feasible(pods, picks, names=names)
        exact_assigned, _ = FullOracle(
            make_oracle_nodes(real_nodes, bound)
        ).schedule(pods)
        relax_placed = int(sum(1 for a in picks if a >= 0))
        exact_placed = int(sum(1 for a in exact_assigned if a >= 0))
        self._megaplan = {
            "pods": len(pods),
            "relax_placed": relax_placed,
            "exact_placed": exact_placed,
            "objective_ratio": round(
                relax_placed / max(exact_placed, 1), 4
            ),
            "plan_valid": not errors,
            "plan_errors": len(errors),
            "iterations": int(stats.iterations),
            "residual": round(float(stats.residual), 4),
            "repaired": int(stats.repaired_pods),
        }

    def _drive_once(self, cycle: int) -> None:
        if self.profile.backlog and cycle == 0 and self.streaming:
            # the seeded mega-backlog drains through the HBM-budget-
            # planned chunked streaming path (Scheduler.drain_backlog).
            # backlog_force_split hands the planner a budget one byte
            # below the base chunk's own estimate, so the auto-split
            # path engages deterministically (the CI smoke pins
            # budget_splits >= 1 off this)
            from ..solver import budget as hbm

            chunk = self.profile.backlog_chunk or self.profile.batch_size
            budget_bytes = 0
            if self.profile.backlog_force_split:
                shape = self.scheduler.drain_shape(chunk)
                budget_bytes = hbm.estimate(shape).per_device_bytes - 1
            if self.profile.backlog_warm_start:
                # mega-planner probe on the FROZEN pre-drain snapshot:
                # relax+repair vs the sequential oracle anchor —
                # check_megaplan asserts validity + the ratio floor
                self._megaplan_probe()
            report = self.scheduler.drain_backlog(
                chunk_pods=chunk, budget_bytes=budget_bytes,
                warm_start=self.profile.backlog_warm_start or None,
            )
            self._backlog_report = report
            for r in report.results:
                self.tracker.record_results(r.scheduled)
                self._sched_bound.update(k for k, _ in r.scheduled)
            return
        if self.streaming:
            try:
                results = self.scheduler.run_streaming(max_batches=200)
            except ExtenderError:
                self._extender_aborts += 1
                return
            for r in results:
                self.tracker.record_results(r.scheduled)
                self._sched_bound.update(k for k, _ in r.scheduled)
            return
        if self.pipelined:
            try:
                results = self.scheduler.run_pipelined(max_batches=200)
            except ExtenderError:
                # extender configs pipeline now (the verdict fold is a
                # pre-dispatch host stage), so a non-ignorable extender
                # abort can surface here; completed batches' results are
                # lost with the raise — acceptable for this corner, and
                # why the extender_flaky profile defaults to the sync
                # drive (profiles.py)
                self._extender_aborts += 1
                return
            for r in results:
                self.tracker.record_results(r.scheduled)
                self._sched_bound.update(k for k, _ in r.scheduled)
            return
        # sync mode drives batch-by-batch (observationally identical to
        # run_until_settled) so an injected non-ignorable extender abort
        # ends the DRIVE without discarding earlier batches' results —
        # losing them would silently weaken the double-bind tracker
        # (review-caught). The scheduler's unhandled-requeue path owns
        # the aborted batch's pods; the lost-pod invariant verifies it.
        for _ in range(200):
            try:
                r = self.scheduler.schedule_batch()
            except ExtenderError:
                self._extender_aborts += 1
                return  # retry next cycle / settle round
            if not r.progressed:
                return
            self.tracker.record_results(r.scheduled)
            self._sched_bound.update(k for k, _ in r.scheduled)

    def _check(self, cycle: int) -> None:
        self.tracker.drain(cycle, self.violations)
        check_capacity(self.cluster, cycle, self.violations)
        check_constraints(self.cluster, cycle, self.violations)
        # every cycle, every profile: a no-op without gang labels, and
        # the gang tentpole's core contract when they exist
        check_no_partial_gangs(self.cluster, cycle, self.violations)
        check_lost_pods(
            self.cluster,
            self.scheduler,
            cycle,
            self.violations,
            undelivered=self.bus.pending_pod_adds,
        )
        self.monotonic.observe(cycle, self.violations)

    def _settled(self) -> bool:
        if self.scheduler._waiting or self.scheduler._in_flight:
            return False
        live = set(self.scheduler.queue.entries().values())
        return not (live & {"active", "backoff"})

    # -- the run --

    def run(self) -> SimResult:
        replaying = self._reader is not None
        for cycle in range(self.cycles):
            metrics.sim_cycles_total.inc()
            if replaying:
                events = [
                    {k: v for k, v in rec.items() if k not in ("k", "c")}
                    for rec in self._reader.events_by_cycle.get(cycle, [])
                ]
            else:
                events = self.generator.generate(cycle)
            self.bind_injector.suspended = True
            try:
                for ev in events:
                    if not replaying:
                        self.trace.event(cycle, **ev)
                    apply_event(self.cluster, ev)
                    self._events_applied += 1
            finally:
                self.bind_injector.suspended = False
            self.clock.advance(1.0)
            if self.bus.delaying and self.bus.pending:
                pending = len(self.bus.pending)
                self.bus.pump(
                    self.journal.decide(
                        "prepump",
                        lambda: self._fault_rng.randint(0, pending),
                    )
                )
            if (
                cycle == self.profile.shift_at
                and self.scheduler.tuner is not None
            ):
                self._tuner_settled_at_shift = (
                    self.scheduler.tuner.settled()
                )
            if (
                self.crash_injector is not None
                and cycle == self.profile.crash_at
            ):
                # kill the scheduler at this cycle's first commit
                # point: pods assumed + approved, nothing bound
                self.crash_injector.arm()
            self._drive(cycle)
            self._permit_verdicts()
            self._check(cycle)

        settled = self._quiesce()
        if not settled:
            _record(
                self.violations, "progress", self.cycles + self.max_settle_rounds,
                "scheduler failed to quiesce after churn stopped "
                f"({self.max_settle_rounds} settle rounds): "
                f"queue={self.scheduler.queue.pending_counts()} "
                f"waiting={len(self.scheduler._waiting)}",
            )
        return self._finish(settled)

    def _permit_verdicts(self) -> None:
        """Allow or abandon (→ virtual-clock timeout) parked WaitingPods,
        one journaled decision each."""
        if self.permit_plugin is None:
            return
        waiting = self.scheduler.waiting_pods()
        for key in sorted(waiting):
            wp = waiting[key]
            allow = self.journal.decide(
                "permit_verdict",
                lambda: int(self._fault_rng.random() < 0.5),
            )
            if allow:
                wp.allow(self.permit_plugin.name())
            # else: left to expire; the settle loop's clock advances
            # cross the deadline and the next cycle rejects + requeues

    def _quiesce(self) -> bool:
        """Churn has stopped: stop injecting, deliver every held event,
        and drain on an advancing virtual clock — through the backoff
        horizon and (once) the 5-minute unschedulable leftover flush —
        until the scheduler goes quiet."""
        self.bind_injector.settling = True
        if self.ext_transport is not None:
            self.ext_transport.settling = True
        if self.permit_plugin is not None:
            self.permit_plugin.settling = True
        if self.solver_injector is not None:
            # device-fault injection stops (transient outages end);
            # poison pods keep failing — they are data, not weather,
            # and must stay terminally quarantined through settle
            self.solver_injector.settling = True
        if self.scheduler.tuner is not None:
            # the draining tail is teardown, not a workload: freeze the
            # tuner so quiescence (batch sizes collapsing to the
            # leftovers) cannot read as a workload shift and unsettle
            # controllers with nothing left to re-converge on. The
            # tuning invariant therefore asserts the state AT churn
            # end: engaged, settled, shift-detected, zero breaches.
            self.scheduler.tuner.frozen = True
        self.bus.pump_all()
        # 11s rounds clear max backoff (10s) and permit timeouts; the
        # 301s round forces the unschedulable-leftover flush. The flush
        # round is MANDATORY before declaring quiescence: pods parked
        # unschedulable by injected faults (extender outages, bind
        # conflicts) see no waking cluster event once churn stops — the
        # 5-minute flush is the only path back, and skipping it would
        # misread "parked by a fault" as "settled" (sim-caught).
        advances = [11.0, 11.0, 301.0] + [11.0] * max(
            self.max_settle_rounds - 3, 0
        )
        flush_round = 2
        for i, adv in enumerate(advances):
            cycle = self.cycles + i
            self.clock.advance(adv)
            self._drive(cycle)
            self._permit_verdicts()
            self._check(cycle)
            if i >= flush_round and self._settled():
                return True
        return False

    def _finish(self, settled: bool) -> SimResult:
        # trace completeness (the obs tentpole's sim contract): every
        # pod this scheduler owned has a journal history ending in a
        # terminal outcome — merged ACROSS incarnations when a crash
        # retired one mid-run (the recovery pass's terminal 'recovered'
        # records must close every history the crash left dangling)
        journal = self.scheduler.journal
        journal_sets = self._dead_journals + [list(journal.lines)]
        check_journal_completeness(
            self.cluster,
            self.scheduler,
            self.cycles + self.max_settle_rounds,
            self.violations,
            merged_last_outcomes(journal_sets),
            self._sched_bound,
            undelivered=self.bus.pending_pod_adds(),
        )
        import json as _json

        recovered_records = sum(
            1
            for lines in journal_sets
            for line in lines
            if _json.loads(line)["outcome"] == "recovered"
        )
        if self.profile.crash_at >= 0:
            check_recovery(
                self.cycles + self.max_settle_rounds,
                self.violations,
                crash_expected=True,
                crashes=(
                    self.crash_injector.crashes
                    if self.crash_injector is not None
                    else 0
                ),
                incarnations=self.incarnations,
                orphans_at_restart=self._orphans_at_restart,
                recovered_records=recovered_records,
            )
        if self.solver_injector is not None:
            # solver-boundary chaos acceptance: fallback engaged,
            # breaker back at the top tier, poison isolated
            check_resilience(
                self.scheduler,
                self.cycles + self.max_settle_rounds,
                self.violations,
                device_faults=self.solver_injector.injected,
                poison_hits=self.solver_injector.poison_hits,
            )
        rebalance_summary = None
        if self.profile.rebalance:
            reb = self.scheduler.rebalancer
            if reb is not None:
                reb.reconcile(self.cluster)
            overruns = (
                self.rebalance_tracker.pdb_overruns
                if self.rebalance_tracker is not None
                else 0
            )
            final_packing = packed_utilization(self.cluster)
            check_rebalance(
                self.cycles + self.max_settle_rounds,
                self.violations,
                history=reb.history if reb is not None else [],
                budget=self.profile.rebalance_budget,
                pdb_overruns=overruns,
                migrations_completed=(
                    reb.migrations_completed if reb is not None else 0
                ),
                # the last churn cycle drives at t == cycles; only
                # passes strictly after it are churn-free, so the
                # monotonicity window opens at cycles + 1
                churn_end_t=float(self.cycles) + 1.0,
                final_packing=final_packing,
            )
            rebalance_summary = {
                **(reb.stats() if reb is not None else {}),
                "tracker_evictions": (
                    self.rebalance_tracker.evictions
                    if self.rebalance_tracker is not None
                    else 0
                ),
                "pdb_overruns": overruns,
                "final_packing": round(final_packing, 4),
            }
        gang_summary = None
        if self._gang_profile:
            from ..gang import GangTracker

            gang_bound: set[str] = set()
            gang_unbound: set[str] = set()
            for p in self.cluster.list_pods():
                gid = GangTracker.gang_of(p)
                if gid is not None:
                    (gang_bound if p.node_name else gang_unbound).add(gid)
            gang_summary = {
                # the headline number the CI smoke pins to 0: gangs
                # with both bound and unbound live members at the end
                "partial_gangs": len(gang_bound & gang_unbound),
                **{
                    k: int(_counter_value(c) - self._gang_counters0[k])
                    for k, c in _GANG_COUNTERS.items()
                },
            }
        tuning_summary = None
        tuned_doc = None
        if self.tuning and self.scheduler.tuner is not None:
            # all python-side counters over the virtual clock, so
            # same-seed runs stay byte-identical through the footer
            tuning_summary = self.scheduler.tuner.summary()
            from ..tuning.profile import tuned_profile

            tuned_doc = tuned_profile(self.scheduler)
            check_tuning(
                self.cycles + self.max_settle_rounds,
                self.violations,
                summary=tuning_summary,
                # only fair when the tuner had SETTLED before the
                # shift: detection compares against the settled
                # baseline, which a still-converging tuner doesn't
                # have yet
                expect_shift=self.profile.shift_at >= 0
                and self._tuner_settled_at_shift,
            )
        telemetry_summary = None
        if self.profile.telemetry and self.scheduler.telemetry is not None:
            tel = self.scheduler.telemetry
            bsnap = (
                tel.bundles.snapshot() if tel.bundles is not None else {}
            )
            # counts only — no paths, no wall timings — so the
            # --selfcheck re-run (which omits the bundle directory)
            # produces a byte-identical footer
            telemetry_summary = {
                "anomalies": len(tel.anomalies),
                "anomaly_signals": sorted(
                    {a.signal for a in tel.anomalies}
                ),
                "bundles_captured": int(bsnap.get("captures", 0)),
                "bundle_triggers": {
                    k: bsnap["by_trigger"][k]
                    for k in sorted(bsnap.get("by_trigger", {}))
                },
            }
            check_telemetry(
                self.cycles + self.max_settle_rounds,
                self.violations,
                summary=telemetry_summary,
                bundle_dir=self.bundle_dir,
            )
        megaplan_summary = None
        if self.profile.backlog_warm_start:
            # merge the pre-drain probe with the drain report's
            # warm-start counters (ranked pods, relax iterations) —
            # check_megaplan needs both sides to call the feature
            # engaged non-vacuously
            rep = self._backlog_report
            megaplan_summary = dict(self._megaplan or {})
            megaplan_summary["ranked"] = (
                rep.warm_start_ranked if rep is not None else 0
            )
            if not megaplan_summary.get("iterations"):
                megaplan_summary["iterations"] = (
                    rep.relax_iterations if rep is not None else 0
                )
            check_megaplan(
                self.cycles + self.max_settle_rounds,
                self.violations,
                summary=megaplan_summary if self._megaplan else None,
            )
        bindings = {
            p.key: p.node_name
            for p in sorted(self.cluster.list_pods(), key=lambda q: q.key)
            if p.node_name
        }
        unbound = sorted(
            p.key for p in self.cluster.list_pods() if not p.node_name
        )
        deltas = {
            k: _counter_value(c) - self._counters0[k]
            for k, c in _DELTA_COUNTERS.items()
        }
        import hashlib

        all_lines = [line for lines in journal_sets for line in lines]
        journal_digest = hashlib.sha256(
            ("\n".join(all_lines) + "\n").encode()
        ).hexdigest()
        summary = {
            "pipelined": self.pipelined,
            "streaming": self.streaming,
            "events": self._events_applied,
            "bound": len(bindings),
            "unbound": len(unbound),
            "settled": settled,
            "violations": len(self.violations),
            "bind_faults": self.bind_injector.injected,
            "watch_delivered": self.bus.delivered,
            "watch_duplicated": self.bus.duplicated,
            "extender_aborts": self._extender_aborts,
            "permit_stalls": (
                self.permit_plugin.stalls if self.permit_plugin else 0
            ),
            "solver_faults": (
                self.solver_injector.injected
                if self.solver_injector
                else 0
            ),
            "poison_hits": (
                self.solver_injector.poison_hits
                if self.solver_injector
                else 0
            ),
            # breaker-state footer (the resilience invariant's
            # assertion target): ladder, trips/recloses/probes, and
            # the current tier per profile — all python-side counters,
            # so same-seed runs stay byte-identical
            "resilience": self.scheduler.resilience.summary(),
            "quarantined": sorted(
                self.scheduler._quarantine_counts
            ),
            # process lifecycle (crash_restart): incarnations that ran,
            # crashes injected, terminal 'recovered' records the fresh
            # incarnation journaled for crash-orphaned pods
            "incarnations": self.incarnations,
            "crashes": (
                self.crash_injector.crashes
                if self.crash_injector is not None
                else 0
            ),
            "recovered_records": recovered_records,
            # continuous rebalancer (the fragmentation profile): pass
            # history, eviction counts from the independent tracker,
            # PDB overruns (must be 0), final packed utilization
            "rebalance": rebalance_summary,
            # closed-loop auto-tuning (tuning_convergence / --tuning):
            # probes/moves/settled/shifts/guardrail counters + final
            # knob values — the tuning invariant's assertion target
            "tuning": tuning_summary,
            # gang scheduling (gang profiles): partial_gangs must be 0
            # (the atomic-commit contract) and quarantined_gangs >= 1
            # when the profile seeds a never-satisfiable gang — both
            # pinned by the CI gang smoke
            "gang": gang_summary,
            # flight telemetry (telemetry profiles): anomaly + capture
            # counts — the telemetry invariant's assertion target; the
            # CI telemetry smoke greps these off the footer line
            "telemetry": telemetry_summary,
            # backlog drain (backlog_drain profiles): counts only —
            # all driver-side and deterministic, so same-seed runs
            # stay byte-identical (wall timings deliberately excluded)
            "backlog": (
                {
                    "pods": self._backlog_report.pods,
                    "drained": self._backlog_report.drained,
                    "chunks": self._backlog_report.chunks,
                    "chunk_pods": self._backlog_report.chunk_pods,
                    "budget_splits": self._backlog_report.budget_splits,
                    "stream_chained": (
                        self._backlog_report.stream_chained_batches
                    ),
                }
                if self._backlog_report is not None
                else None
            ),
            # convex-relaxation mega-planner (megaplan profiles): the
            # pre-drain probe's validity/ratio verdict + warm-start
            # counters — check_megaplan's assertion target; counts and
            # rounded ratios only (byte-identical under --selfcheck)
            "megaplan": megaplan_summary,
            # the journal digest rides in the footer, so the trace
            # selfcheck also proves journal byte-identity across runs
            # (all incarnations' lines, in incarnation order)
            "journal_records": len(all_lines),
            "journal_digest": journal_digest,
            **deltas,
        }
        self.trace.footer(
            bindings=bindings,
            unbound=unbound,
            violations=[v.as_dict() for v in self.violations],
            summary=summary,
        )
        divergence = None
        if self._reader is not None:
            divergence = self._diff_replay(bindings)
        flight_dump = None
        if self.violations and self.scheduler.flight is not None:
            # the invariant trigger: dump the recent-history ring next
            # to the violation report (no-op without a configured path)
            flight_dump = self.scheduler.flight.dump(
                path=self.flight_dump_path, trigger="invariant"
            )
        return SimResult(
            profile=self.profile.name,
            seed=self.seed,
            cycles=self.cycles,
            bindings=bindings,
            unbound=unbound,
            violations=self.violations,
            settled=settled,
            summary=summary,
            trace=self.trace,
            replay_divergence=divergence,
            journal_lines=all_lines,
            flight_dump=flight_dump,
            tuned_profile=tuned_doc,
        )

    def _diff_replay(self, bindings: dict[str, str]) -> str | None:
        footer = self._reader.footer
        if footer is None:
            return "trace has no footer (recorded run died mid-write)"
        if self.journal.leftover():
            return (
                f"{self.journal.leftover()} recorded decisions were never "
                "consumed (the replayed run took a shorter path)"
            )
        recorded = footer.get("bindings") or {}
        if recorded != bindings:
            gone = sorted(set(recorded) - set(bindings))
            new = sorted(set(bindings) - set(recorded))
            moved = sorted(
                k
                for k in set(recorded) & set(bindings)
                if recorded[k] != bindings[k]
            )
            return (
                "final bindings diverged from the recorded footer: "
                f"missing={gone[:5]} extra={new[:5]} moved={moved[:5]} "
                f"(recorded {len(recorded)} vs replayed {len(bindings)})"
            )
        return None


def run_sim(
    profile: str,
    seed: int = 0,
    cycles: int = 10,
    *,
    pipelined: bool | None = None,
    streaming: bool | None = None,
    spans: bool = False,
    flight_dump: str | None = None,
    mesh_devices: int = 1,
    tuning: bool | None = None,
    bundle_dir: str | None = None,
) -> SimResult:
    """One fresh seeded run (library entry; the CLI and tests use this)."""
    return SimHarness(
        profile, seed=seed, cycles=cycles, pipelined=pipelined,
        streaming=streaming, spans=spans, flight_dump=flight_dump,
        mesh_devices=mesh_devices, tuning=tuning, bundle_dir=bundle_dir,
    ).run()


def replay_trace(path) -> SimResult:
    """Re-execute a recorded trace: events and fault decisions replay
    literally; the result's ``replay_divergence`` reports any drift
    from the recorded footer."""
    reader = TraceReader.load(path)
    h = reader.header
    return SimHarness(
        h["profile"],
        seed=int(h["seed"]),
        cycles=int(h["cycles"]),
        pipelined=bool(h["pipelined"]),
        streaming=bool(h.get("streaming", False)),
        tuning=bool(h.get("tuning", False)),
        replay=reader,
    ).run()
