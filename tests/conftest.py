"""Test configuration: force JAX onto CPU with 8 virtual devices BEFORE any
test imports jax, so sharding tests exercise a multi-chip mesh without TPU
hardware (SURVEY.md §6.7) and resource arithmetic stays int64.

NOTE: on this box (jax 0.9 + axon PJRT) the JAX_PLATFORMS / JAX_ENABLE_X64
environment variables are NOT honored — only jax.config.update works, so we
import jax here (conftest runs first) and set config explicitly.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# int64 resource arithmetic (memory bytes overflow int32) — parity requires it
jax.config.update("jax_enable_x64", True)
