"""Crash/restart statelessness (SURVEY §6.3): the scheduler holds no
durable state — a fresh Scheduler over the same ClusterState resyncs via
the initial informer sync and continues correctly, including in-flight
preemption intent persisted in pod.status.nominatedNodeName."""

import tempfile

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils import tracing


def _cfg():
    return SchedulerConfig(solver=ExactSolverConfig(tie_break="first"))


def test_restart_resumes_pending_and_nominations():
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    s1 = Scheduler(cs, _cfg(), clock=clock)

    # schedule one pod, preempt for another, then "crash" (drop s1)
    victim = MakePod().name("victim").priority(0).req({"cpu": "2"}).obj()
    cs.create_pod(victim)
    cs.bind("default", "victim", "n")
    cs.create_pod(MakePod().name("preemptor").priority(10).req({"cpu": "2"}).obj())
    r = s1.schedule_batch()
    assert r.preemptions
    assert cs.get_pod("default", "preemptor").nominated_node_name == "n"

    # restart: a NEW scheduler over the same cluster state must pick up the
    # pending preemptor (initial sync), honor its persisted nomination, and
    # protect it from a thief that arrived during the outage
    cs.create_pod(MakePod().name("thief").priority(1).req({"cpu": "2"}).obj())
    clock.advance(30.0)
    s2 = Scheduler(cs, _cfg(), clock=clock)
    assert "default/preemptor" in s2.nominated_pods
    r = s2.schedule_batch()
    placed = dict(r.scheduled)
    assert placed.get("default/preemptor") == "n"
    assert "default/thief" in r.unschedulable


def test_restart_reconstructs_bound_state():
    """Bound pods re-enter the cache on restart: a full node stays full."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    s1 = Scheduler(cs, _cfg(), clock=clock)
    cs.create_pod(MakePod().name("a").req({"cpu": "2"}).obj())
    assert dict(s1.schedule_batch().scheduled).get("default/a") == "n"

    s2 = Scheduler(cs, _cfg(), clock=clock)
    cs.create_pod(MakePod().name("b").req({"cpu": "2"}).obj())
    r = s2.schedule_batch()
    assert "default/b" in r.unschedulable or r.preemptions == []


def test_tracing_wraps_schedule_batch(tmp_path):
    """--trace-dir plumbing: enabling tracing must not change behavior and
    must produce a trace directory when solves run."""
    tracing.enable(str(tmp_path))
    try:
        clock = FakeClock()
        cs = ClusterState()
        cs.create_node(
            MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
        )
        sched = Scheduler(cs, _cfg(), clock=clock)
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = sched.schedule_batch()
        assert dict(r.scheduled).get("default/p") == "n"
    finally:
        tracing.stop()
        tracing._trace_dir = None
    assert any(tmp_path.iterdir())  # the profiler wrote a session dir
