"""HillClimber: the bounded per-knob controller.

One integer knob, one objective to maximize. The climber alternates
between MEASURING the incumbent value and PROBING a neighbor (value *
step up, value // step down — geometric because every governed knob is
a size/depth whose useful range spans octaves). The machine is built
around three safety properties the convergence tests pin:

- **hysteresis**: a probe is accepted only when its objective beats the
  incumbent's by a strict margin (``obj > baseline * (1 + hysteresis)``).
  An A->B acceptance therefore implies obj(B) > obj(A) by the margin,
  and a later B->A acceptance would need obj(A) > obj(B) by the margin
  within the same regime — so A<->B oscillation requires the objective
  itself to move, which is the workload-shift case the runtime handles
  by explicit ``unsettle``.
- **revert on regression**: a rejected probe restores the incumbent
  value immediately. The knob never stays at a measured-worse setting
  longer than one evaluation window, which is what makes the tuned
  bench arm ">= static" by construction rather than by luck.
- **settle detection**: after both directions fail to improve
  ``settle_after`` times, the climber stops proposing entirely (zero
  steady-state overhead). ``unsettle`` re-opens it.

Guardrails are the ``guard`` callable: a candidate failing it is never
applied — not "applied then rolled back", never applied — and the
rejection is counted. This is how the drain-chunk controller keeps the
HBM budget assertion (solver/budget.py) BETWEEN the proposal and the
dispatch path.

Pure python, no clocks, no randomness: a seeded objective trace drives
the controller to a deterministic decision sequence (the property-test
contract).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Decision:
    """One journaled controller action. ``action`` is probe (try a
    neighbor), accept (probe won, it is the new incumbent), revert
    (probe lost, incumbent restored), settle (stop proposing),
    unsettle (workload shift re-opened tuning)."""

    knob: str
    action: str
    old: int
    new: int
    objective: float
    baseline: float
    trigger: dict = field(default_factory=dict)


# state-machine phases
_MEASURE = "measure"  # accumulating objective at the incumbent value
_PROBE = "probe"  # accumulating objective at a candidate value


class HillClimber:
    def __init__(
        self,
        knob: str,
        value: int,
        lo: int,
        hi: int,
        *,
        step: int = 2,
        hysteresis: float = 0.05,
        settle_after: int = 2,
        eval_batches: int = 6,
        guard=None,
        align: int = 1,
        max_probes: int = 16,
    ) -> None:
        if not lo <= value <= hi:
            raise ValueError(
                f"{knob}: initial value {value} outside [{lo}, {hi}]"
            )
        if step < 2:
            raise ValueError(f"{knob}: step must be >= 2 (got {step})")
        self.knob = knob
        self.value = int(value)
        self.lo, self.hi = int(lo), int(hi)
        self.step = step
        self.hysteresis = hysteresis
        self.settle_after = settle_after
        self.eval_batches = max(eval_batches, 1)
        self.guard = guard
        # candidates snap to multiples of ``align`` (the drain chunk
        # must stay group-aligned or the grouped fast path degrades)
        self.align = max(align, 1)
        # bounded experimentation: after this many probes within one
        # episode (construction/unsettle -> settle) the climber settles
        # at its incumbent regardless — a noisy objective whose spurious
        # accepts keep resetting the no-improve streak must still
        # terminate, and a knob that genuinely keeps improving for 16
        # octaves has outgrown its bounds anyway
        self.max_probes = max(max_probes, 1)
        self._probes_episode = 0

        self._phase = _MEASURE
        self._obj: list[tuple[float, float]] = []  # (num, den) pairs
        self._baseline = 0.0
        self._incumbent = self.value  # value to restore on revert
        self._dir = +1  # probe up first (all governed knobs start low)
        self._tried_flip = False
        self._no_improve = 0
        self.settled = False
        self.moves = 0  # accepted moves
        self.probes = 0
        # observations ever received: a controller whose dispatch mode
        # never ran (stream_depth on a pipelined drive) has ticks == 0
        # and must not count against the runtime's settled state — it
        # was never given a chance, which is not a convergence failure
        self.ticks = 0
        self.guard_rejections = 0
        self.unsettles = 0
        self.history: list[Decision] = []

    # -- candidate generation --

    def _snap(self, v: int) -> int:
        v = (v // self.align) * self.align
        return min(max(v, self.lo), self.hi)

    def _candidate(self, direction: int) -> int | None:
        """Next value in ``direction``, aligned and bounded; None when
        the move is a no-op or the guardrail rejects it (the rejection
        is counted — the candidate is never applied)."""
        if direction > 0:
            cand = self._snap(self.value * self.step)
        else:
            cand = self._snap(self.value // self.step)
        if cand == self.value:
            return None
        if self.guard is not None and not self.guard(cand):
            self.guard_rejections += 1
            return None
        return cand

    # -- the drive --

    def observe(
        self,
        num: float,
        den: float = 1.0,
        trigger: dict | None = None,
    ):
        """Feed one batch's objective as a (numerator, denominator)
        pair — pods and wall seconds for the throughput knobs; pass
        ``den=1`` to drive with a plain scalar (then the window score
        is the mean). The window score is the ratio of sums, i.e. true
        window throughput: robust to the bimodal per-batch wall deltas
        a virtual clock produces (intra-cycle batches take 0 s, the
        cycle boundary takes the whole advance — a per-batch-rate
        median would whipsaw across that, a ratio of sums cannot).
        Returns a Decision when an evaluation window completed and the
        controller acted (the runtime applies ``self.value`` after
        every non-None return), else None. A settled controller is
        inert."""
        self.ticks += 1
        if self.settled:
            return None
        self._obj.append((num, den))
        if len(self._obj) < self.eval_batches:
            return None
        score = sum(n for n, _ in self._obj) / max(
            sum(d for _, d in self._obj), 1e-6
        )
        self._obj = []
        trigger = dict(trigger or {})
        trigger["objective"] = round(score, 6)
        if self._phase == _MEASURE:
            self._baseline = score
            return self._start_probe(score, trigger)
        # PROBE window complete: accept or revert
        if score > self._baseline * (1.0 + self.hysteresis):
            old = self._incumbent
            self._incumbent = self.value
            self._baseline = score
            self.moves += 1
            self._no_improve = 0
            self._tried_flip = False
            d = self._decide("accept", old, self.value, score, trigger)
            # keep climbing the winning direction next window
            self._phase = _MEASURE
            return d
        # regression (or no margin): restore the incumbent NOW
        old = self.value
        self.value = self._incumbent
        self._phase = _MEASURE
        if not self._tried_flip:
            self._dir = -self._dir
            self._tried_flip = True
        else:
            self._tried_flip = False
            self._no_improve += 1
            if self._no_improve >= self.settle_after:
                self.settled = True
                return self._decide(
                    "settle", old, self.value, score, trigger
                )
        return self._decide("revert", old, self.value, score, trigger)

    def _start_probe(self, score: float, trigger: dict):
        if self._probes_episode >= self.max_probes:
            # probe budget exhausted: terminate the episode at the
            # incumbent (already restored by the revert path)
            self.settled = True
            return self._decide(
                "settle", self.value, self.value, score, trigger
            )
        cand = self._candidate(self._dir)
        if cand is None:
            self._dir = -self._dir
            cand = self._candidate(self._dir)
        if cand is None:
            # neither direction has a legal candidate (bounds or
            # guardrail): nothing to try — settle immediately
            self._no_improve += 1
            if self._no_improve >= self.settle_after:
                self.settled = True
                return self._decide(
                    "settle", self.value, self.value, score, trigger
                )
            return None
        old = self.value
        self.value = cand
        self._phase = _PROBE
        self.probes += 1
        self._probes_episode += 1
        return self._decide("probe", old, cand, score, trigger)

    def _decide(
        self, action: str, old: int, new: int, objective: float, trigger: dict
    ) -> Decision:
        d = Decision(
            knob=self.knob,
            action=action,
            old=old,
            new=new,
            objective=objective,
            baseline=self._baseline,
            trigger=trigger,
        )
        self.history.append(d)
        return d

    def abort_probe(self) -> None:
        """The runtime could not apply the current probe value (an
        apply-time guard breach): restore the incumbent and return to
        measuring it. Without this the climber would keep attributing
        the incumbent's scores to the never-applied candidate — and a
        noise accept would then install the rejected value through the
        accept path, which deliberately skips the guard."""
        self.value = self._incumbent
        self._phase = _MEASURE
        self._obj = []

    def unsettle(self, trigger: dict | None = None) -> Decision:
        """A workload shift invalidated the settled point: re-open
        tuning from the current value (the best known for the OLD
        regime — still the sanest starting point for the new one)."""
        self.settled = False
        self._phase = _MEASURE
        self._obj = []
        self._baseline = 0.0
        self._incumbent = self.value
        self._dir = +1
        self._tried_flip = False
        self._no_improve = 0
        self._probes_episode = 0
        self.unsettles += 1
        return self._decide(
            "unsettle", self.value, self.value, 0.0, dict(trigger or {})
        )
