"""Pod/Node object model: wire round-trip, resource computation, selectors,
tolerations — semantics from framework/types.go and util/non_zero.go."""

from kubernetes_tpu.api.labels import (
    Requirement,
    Selector,
    selector_from_label_selector,
)
from kubernetes_tpu.api.objects import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    Pod,
    Node,
    Taint,
    Toleration,
)
from kubernetes_tpu.api.wrappers import MakeNode, MakePod


class TestSelectors:
    def test_match_labels(self):
        sel = selector_from_label_selector({"matchLabels": {"app": "web"}})
        assert sel.matches({"app": "web", "x": "y"})
        assert not sel.matches({"app": "db"})
        assert not sel.matches({})

    def test_empty_selector_matches_everything(self):
        sel = selector_from_label_selector({})
        assert sel.matches({}) and sel.matches({"a": "b"})

    def test_nil_selector(self):
        assert selector_from_label_selector(None) is None

    def test_operators(self):
        assert Requirement("k", "In", ("a", "b")).matches({"k": "a"})
        assert not Requirement("k", "In", ("a",)).matches({})
        assert Requirement("k", "NotIn", ("a",)).matches({})  # absent => NotIn true
        assert Requirement("k", "NotIn", ("a",)).matches({"k": "b"})
        assert not Requirement("k", "NotIn", ("a",)).matches({"k": "a"})
        assert Requirement("k", "Exists").matches({"k": ""})
        assert not Requirement("k", "Exists").matches({})
        assert Requirement("k", "DoesNotExist").matches({})
        assert Requirement("k", "Gt", ("5",)).matches({"k": "6"})
        assert not Requirement("k", "Gt", ("5",)).matches({"k": "5"})
        assert not Requirement("k", "Gt", ("5",)).matches({"k": "abc"})
        assert not Requirement("k", "Gt", ("5",)).matches({})
        assert Requirement("k", "Lt", ("5",)).matches({"k": "4"})

    def test_and_of_requirements(self):
        sel = Selector(
            (Requirement("a", "In", ("1",)), Requirement("b", "Exists"))
        )
        assert sel.matches({"a": "1", "b": "x"})
        assert not sel.matches({"a": "1"})


class TestPodResources:
    def test_sum_containers_plus_overhead(self):
        p = (
            MakePod()
            .name("p")
            .req({"cpu": "100m", "memory": "100Mi"})
            .req({"cpu": "200m", "memory": "50Mi"})
            .overhead({"cpu": "10m"})
            .obj()
        )
        r = p.resource_request()
        assert r["cpu"] == 310
        assert r["memory"] == 150 * 1024**2

    def test_init_container_max(self):
        p = (
            MakePod()
            .name("p")
            .req({"cpu": "100m"})
            .init_req({"cpu": "500m"})
            .init_req({"cpu": "300m"})
            .obj()
        )
        assert p.resource_request()["cpu"] == 500

    def test_sidecar_init_container_adds(self):
        p = (
            MakePod()
            .name("p")
            .req({"cpu": "100m"})
            .init_req({"cpu": "50m"}, restart_policy="Always")
            .obj()
        )
        assert p.resource_request()["cpu"] == 150

    def test_non_zero_defaults(self):
        p = MakePod().name("p").obj()  # one container, zero requests
        cpu, mem = p.non_zero_request()
        assert cpu == DEFAULT_MILLI_CPU_REQUEST
        assert mem == DEFAULT_MEMORY_REQUEST

    def test_non_zero_with_real_requests(self):
        p = MakePod().name("p").req({"cpu": "250m", "memory": "1Gi"}).obj()
        assert p.non_zero_request() == (250, 1024**3)

    def test_non_zero_partial(self):
        # cpu set, memory zero -> memory defaults
        p = MakePod().name("p").req({"cpu": "250m"}).obj()
        assert p.non_zero_request() == (250, DEFAULT_MEMORY_REQUEST)


class TestTolerations:
    def test_exact_match(self):
        t = Toleration(key="k", operator="Equal", value="v", effect="NoSchedule")
        assert t.tolerates(Taint("k", "v", "NoSchedule"))
        assert not t.tolerates(Taint("k", "w", "NoSchedule"))
        assert not t.tolerates(Taint("k", "v", "NoExecute"))

    def test_exists(self):
        t = Toleration(key="k", operator="Exists")
        assert t.tolerates(Taint("k", "anything", "NoSchedule"))
        assert t.tolerates(Taint("k", "", "NoExecute"))

    def test_empty_key_exists_tolerates_all(self):
        t = Toleration(operator="Exists")
        assert t.tolerates(Taint("any", "v", "NoSchedule"))

    def test_empty_effect_matches_all_effects(self):
        t = Toleration(key="k", operator="Exists", effect="")
        assert t.tolerates(Taint("k", "", "NoExecute"))


class TestWireRoundTrip:
    def test_pod_round_trip(self):
        p = (
            MakePod()
            .name("web-1")
            .namespace("prod")
            .labels({"app": "web"})
            .priority(100)
            .req({"cpu": "500m", "memory": "1Gi"})
            .node_selector({"disk": "ssd"})
            .toleration("dedicated", "gpu", effect="NoSchedule")
            .spread_constraint(1, "topology.kubernetes.io/zone", match_labels={"app": "web"})
            .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"})
            .obj()
        )
        d = p.to_dict()
        p2 = Pod.from_dict(d)
        assert p2.name == "web-1" and p2.namespace == "prod"
        assert p2.effective_priority == 100
        assert p2.resource_request() == p.resource_request()
        assert p2.node_selector == {"disk": "ssd"}
        assert len(p2.tolerations) == 1
        assert len(p2.topology_spread_constraints) == 1
        assert p2.affinity.pod_anti_affinity.required[0].topology_key == "kubernetes.io/hostname"
        assert p2.to_dict() == d

    def test_node_round_trip(self):
        n = (
            MakeNode()
            .name("node-1")
            .label("topology.kubernetes.io/zone", "us-east1-a")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .taint("dedicated", "gpu", "NoSchedule")
            .image("nginx:1.25", 50_000_000)
            .obj()
        )
        d = n.to_dict()
        n2 = Node.from_dict(d)
        assert n2.name == "node-1"
        assert n2.allocatable["cpu"] == 8000
        assert n2.allocatable["memory"] == 32 * 1024**3
        assert n2.allowed_pod_number == 110
        assert n2.taints[0] == Taint("dedicated", "gpu", "NoSchedule")
        assert n2.images[0].size_bytes == 50_000_000
        assert n2.to_dict() == d

    def test_node_affinity_round_trip(self):
        p = (
            MakePod()
            .name("p")
            .node_affinity_in("zone", ["a", "b"])
            .preferred_node_affinity(10, "disk", ["ssd"])
            .obj()
        )
        p2 = Pod.from_dict(p.to_dict())
        na = p2.affinity.node_affinity
        assert na.required is not None and len(na.required) == 1
        assert na.required[0].matches({"zone": "a"}, {})
        assert not na.required[0].matches({"zone": "c"}, {})
        assert na.preferred[0].weight == 10

    def test_host_ports(self):
        p = MakePod().name("p").host_port(8080).host_port(9090, "UDP").obj()
        assert p.host_ports() == (
            ("0.0.0.0", "TCP", 8080),
            ("0.0.0.0", "UDP", 9090),
        )


class TestReviewRegressions:
    """Regressions from the parity review: sidecar ordering, operator sets,
    resourceVersion round-trip."""

    def test_sidecar_before_init_ordering(self):
        # upstream PodRequests: non-sidecar init's effective request = own +
        # sidecars declared before it. main=100m, sidecar=500m, init=1000m
        # -> max(100+500, 1000+500) = 1500m
        p = (
            MakePod()
            .name("p")
            .req({"cpu": "100m"})
            .init_req({"cpu": "500m"}, restart_policy="Always")
            .init_req({"cpu": "1000m"})
            .obj()
        )
        assert p.resource_request()["cpu"] == 1500

    def test_init_before_sidecar_ordering(self):
        # init declared BEFORE the sidecar sees no sidecar prefix:
        # max(100+500, 1000) = 1000... main+sidecar = 600 -> result 1000
        p = (
            MakePod()
            .name("p")
            .req({"cpu": "100m"})
            .init_req({"cpu": "1000m"})
            .init_req({"cpu": "500m"}, restart_policy="Always")
            .obj()
        )
        assert p.resource_request()["cpu"] == 1000

    def test_non_zero_sidecar_ordering(self):
        # zero-request main (defaults 100m) + sidecar 500m + init 1000m
        # -> max(100+500, 1000+500) = 1600m? No: init defaults apply per
        # container: init cpu=1000m given. max(600, 1500) = 1500
        p = (
            MakePod()
            .name("p")
            .init_req({"cpu": "500m", "memory": "1Gi"}, restart_policy="Always")
            .init_req({"cpu": "1", "memory": "1Gi"})
            .obj()
        )
        cpu, _ = p.non_zero_request()
        assert cpu == 1500

    def test_label_selector_rejects_gt(self):
        import pytest

        with pytest.raises(ValueError):
            selector_from_label_selector(
                {"matchExpressions": [{"key": "k", "operator": "Gt", "values": ["1"]}]}
            )

    def test_node_selector_allows_gt(self):
        p = Pod.from_dict(
            {
                "metadata": {"name": "p"},
                "spec": {
                    "containers": [{"name": "c"}],
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {"matchExpressions": [{"key": "cpus", "operator": "Gt", "values": ["4"]}]}
                                ]
                            }
                        }
                    },
                },
            }
        )
        term = p.affinity.node_affinity.required[0]
        assert term.matches({"cpus": "8"}, {})
        assert not term.matches({"cpus": "4"}, {})

    def test_resource_version_round_trip(self):
        p = Pod.from_dict({"metadata": {"name": "p", "resourceVersion": "42"}, "spec": {"containers": []}})
        assert p.resource_version == 42
        assert Pod.from_dict(p.to_dict()).resource_version == 42
        n = Node.from_dict({"metadata": {"name": "n", "resourceVersion": "7"}})
        assert n.resource_version == 7
        assert Node.from_dict(n.to_dict()).resource_version == 7


class TestReviewRegressions2:
    def test_empty_key_equal_toleration_matches_all_keys(self):
        # toleration.go#ToleratesTaint: empty key does not restrict; Equal
        # compares values
        t = Toleration(key="", operator="Equal", value="v")
        assert t.tolerates(Taint("anykey", "v", "NoSchedule"))
        assert not t.tolerates(Taint("anykey", "w", "NoSchedule"))

    def test_gt_rejects_python_int_leniency(self):
        # Go strconv.ParseInt rejects underscores/unicode digits
        assert not Requirement("k", "Gt", ("5",)).matches({"k": "1_0"})
        assert not Requirement("k", "Gt", ("5",)).matches({"k": "１０"})
        assert Requirement("k", "Gt", ("5",)).matches({"k": "+10"})

    def test_match_labels_wire_shape_preserved(self):
        from kubernetes_tpu.api.labels import label_selector_to_dict

        sel = selector_from_label_selector(
            {"matchLabels": {"app": "web"},
             "matchExpressions": [{"key": "tier", "operator": "Exists"}]}
        )
        d = label_selector_to_dict(sel)
        assert d["matchLabels"] == {"app": "web"}
        assert d["matchExpressions"] == [{"key": "tier", "operator": "Exists", "values": []}]
        # and evaluation still ANDs both parts
        assert sel.matches({"app": "web", "tier": "x"})
        assert not sel.matches({"app": "web"})
