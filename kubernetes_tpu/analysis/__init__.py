"""kubernetes_tpu.analysis — tracer-safety & lock-discipline analyzer.

A self-contained AST static analyzer (stdlib only) for the two bug
classes the batched scheduler cannot afford: accidental host<->device
syncs on the solve hot path (TPU001/TPU002/TPU003) and undisciplined
access to the shared mutable state the pipelined loop threads through
watch ingest (LOCK001), plus metric-name drift (MET001).

Usage::

    python -m kubernetes_tpu.analysis [--json] [paths...]
    findings = analysis.run_paths(["kubernetes_tpu/"])

Annotations and rule semantics: analysis/README.md. The in-process
pytest gate is tests/test_static_analysis.py.
"""

from __future__ import annotations

from pathlib import Path

from .core import (
    AnalysisContext,
    Finding,
    Pass,
    SourceModule,
    apply_suppressions,
    suppression_findings,
)
from .passes import ALL_PASSES
from .registry import default_context

__all__ = [
    "ALL_PASSES",
    "AnalysisContext",
    "Finding",
    "Pass",
    "SourceModule",
    "analyze_module",
    "default_context",
    "run_paths",
]


def analyze_module(
    module: SourceModule,
    ctx: AnalysisContext | None = None,
    passes=None,
) -> list[Finding]:
    """Run the pass set over one parsed module, apply suppressions, and
    enforce the reason requirement (KTPU000)."""
    ctx = ctx or default_context()
    findings: list[Finding] = []
    for cls in passes or ALL_PASSES:
        findings.extend(cls().run(module, ctx))
    apply_suppressions(module, findings)
    findings.extend(suppression_findings(module))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def analyze_source(
    source: str,
    filename: str = "snippet.py",
    ctx: AnalysisContext | None = None,
    passes=None,
) -> list[Finding]:
    """Fixture-test entry point: analyze an in-memory snippet."""
    return analyze_module(
        SourceModule.parse(filename, source=source), ctx=ctx, passes=passes
    )


def collect_files(paths) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.is_file() and p.suffix == ".py":
            files.append(p)
        else:
            # a typo'd path silently scanning nothing would leave a CI
            # gate permanently green (review-caught) — fail loudly
            raise FileNotFoundError(
                f"{p}: not a directory or .py file — nothing to analyze"
            )
    return files


def run_paths(
    paths=None,
    ctx: AnalysisContext | None = None,
    passes=None,
) -> list[Finding]:
    """Analyze files/directories (default: the kubernetes_tpu package
    this module ships in). Returns ALL findings; callers filter on
    ``suppressed`` for gating."""
    if not paths:
        paths = [Path(__file__).resolve().parents[1]]
    ctx = ctx or default_context()
    findings: list[Finding] = []
    for f in collect_files(paths):
        try:
            module = SourceModule.parse(f)
        except SyntaxError as e:
            findings.append(
                Finding(
                    rule="KTPU001",
                    path=str(f),
                    line=e.lineno or 0,
                    message=f"syntax error: {e.msg}",
                )
            )
            continue
        findings.extend(analyze_module(module, ctx=ctx, passes=passes))
    return findings
