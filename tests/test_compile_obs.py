"""kubernetes_tpu/obs/compile.py — compile observability: the
process-wide XLA-compile watcher, scope attribution, the gauge pair,
and the known-shape no-recompile regression (the silent
streaming-hot-path killer this layer exists to catch)."""

import uuid

import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs.compile import WATCHER, CompileWatcher
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState


def _fresh_jit():
    """A jitted function whose HLO is unique per call site, so neither
    the in-process jit cache nor the persistent disk cache can satisfy
    it — its first call MUST compile."""
    salt = int(uuid.uuid4().int % 1_000_003)
    return jax.jit(lambda x: x * salt + (salt % 7))


class TestCompileWatcher:
    def test_counts_fresh_compile_and_caches_repeat(self):
        WATCHER.install()
        f = _fresh_jit()
        x = jnp.arange(4)
        c0, _r0, _s0 = WATCHER.totals()
        f(x).block_until_ready()
        c1, _r1, s1 = WATCHER.totals()
        assert c1 > c0  # the fresh function compiled
        f(x).block_until_ready()  # same shape: cached, no compile
        c2, _r2, _s2 = WATCHER.totals()
        assert c2 == c1

    def test_scope_attribution(self):
        WATCHER.install()
        f = _fresh_jit()
        with WATCHER.scope("test-scope-A") as scope:
            f(jnp.arange(8)).block_until_ready()
            compiles, seconds = scope.delta()
        assert compiles >= 1
        assert seconds > 0.0
        counts = WATCHER.scope_counts()
        assert counts["test-scope-A"][0] >= 1

    def test_gauge_pair_tracks_keys_and_recompiles(self):
        WATCHER.install()
        with WATCHER.scope(f"gauge-scope-{uuid.uuid4().hex[:8]}"):
            _fresh_jit()(jnp.arange(4)).block_until_ready()
        keys = metrics.xla_compile_cache_keys._value.get()
        assert keys >= 1
        # recompilations = compiles beyond the first per scope; the
        # fresh scope above compiled once, so it contributes zero
        assert metrics.xla_recompilations._value.get() >= 0

    def test_uninstalled_watcher_is_inert(self):
        w = CompileWatcher()  # never installed: no listener
        with w.scope("x") as s:
            _fresh_jit()(jnp.arange(4)).block_until_ready()
            assert s.delta() == (0, 0.0)


class TestKnownShapeRegression:
    def test_second_identical_batch_compiles_nothing(self):
        """THE regression gate: a batch shape the scheduler already
        solved must not compile again — a recompile for a known shape
        at sustained-stream scale turns a ~ms dispatch into a
        multi-second stall, silently."""
        cs = ClusterState()
        for i in range(4):
            cs.create_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": "32"})
                .obj()
            )
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8,
                solver=ExactSolverConfig(tie_break="first"),
            ),
        )
        # TWO warm batches: the first compiles the solve pipeline
        # (fresh session), the second the dirty-column heal program
        # (first exercised once the session exists)
        for round_ in range(2):
            for i in range(4):
                cs.create_pod(
                    MakePod().name(f"warm{round_}-{i}")
                    .namespace("default").req({"cpu": "100m"}).obj()
                )
            r = sched.schedule_batch()
            assert len(r.scheduled) == 4
        c0, _r, _s = WATCHER.totals()
        for i in range(4):
            cs.create_pod(
                MakePod().name(f"again{i}").namespace("default")
                .req({"cpu": "100m"}).obj()
            )
        r = sched.schedule_batch()  # identical shape: must be warm
        assert len(r.scheduled) == 4
        c1, _r, _s = WATCHER.totals()
        assert c1 == c0, (
            f"known-shape batch recompiled ({c1 - c0} compiles) — "
            "the streaming hot path would pay this stall per batch"
        )

    def test_dispatch_scope_is_bracketed(self):
        """The scheduler brackets dispatches with a shape-keyed scope:
        after a solve, the watcher holds a scope named for the profile
        + padded shape (span attribution reads the same bracket)."""
        cs = ClusterState()
        cs.create_node(
            MakeNode().name("n0")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "32"}).obj()
        )
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=4,
                solver=ExactSolverConfig(tie_break="first"),
            ),
        )
        cs.create_pod(
            MakePod().name("p0").namespace("default")
            .req({"cpu": "100m"}).obj()
        )
        sched.schedule_batch()
        assert any(
            k.startswith("default-scheduler:p")
            for k in WATCHER.scope_counts()
        )
