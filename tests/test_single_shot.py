"""Single-shot solver: feasibility, work conservation, priority dominance,
and scale smoke (the 50k x 10k config runs on the real TPU via bench.py)."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.solver.single_shot import SingleShotConfig, SingleShotSolver
from kubernetes_tpu.tensorize.plugins import build_static_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)


def solve(nodes, pods, **cfg):
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    solver = SingleShotSolver(SingleShotConfig(**cfg))
    a = solver.solve(nbatch, pbatch, static)
    return a, nbatch


def check_feasible(nodes, pods, assignments):
    """Every placement respects allocatable + pod-count + schedulability."""
    used = {n.name: {} for n in nodes}
    count = {n.name: 0 for n in nodes}
    for pod, a in zip(pods, assignments):
        if a < 0:
            continue
        node = nodes[a]
        assert not node.unschedulable
        count[node.name] += 1
        for k, v in pod.resource_request().items():
            used[node.name][k] = used[node.name].get(k, 0) + v
    for n in nodes:
        assert count[n.name] <= n.allowed_pod_number, n.name
        for k, v in used[n.name].items():
            assert v <= n.allocatable.get(k, 0), (n.name, k)


def test_all_place_when_capacity_suffices():
    nodes = [
        MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "32Gi", "pods": "20"}).obj()
        for i in range(8)
    ]
    pods = [
        MakePod().name(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
        for i in range(64)
    ]
    a, _ = solve(nodes, pods)
    assert all(x >= 0 for x in a)
    check_feasible(nodes, pods, a)


def test_work_conservation_overload():
    nodes = [
        MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "16Gi", "pods": "100"}).obj()
        for i in range(2)
    ]
    # 12 pods of 1 cpu into 8 cpus: exactly 8 place
    pods = [MakePod().name(f"p{i}").req({"cpu": "1"}).obj() for i in range(12)]
    a, _ = solve(nodes, pods)
    assert int((a >= 0).sum()) == 8
    check_feasible(nodes, pods, a)


def test_priority_dominance_under_scarcity():
    nodes = [MakeNode().name("n0").capacity({"cpu": "2", "memory": "8Gi", "pods": "10"}).obj()]
    pods = [
        MakePod().name(f"lo{i}").req({"cpu": "1"}).priority(1).obj() for i in range(4)
    ] + [
        MakePod().name(f"hi{i}").req({"cpu": "1"}).priority(100).obj() for i in range(2)
    ]
    a, _ = solve(nodes, pods)
    placed = {pods[i].name for i in range(6) if a[i] >= 0}
    assert placed == {"hi0", "hi1"}
    check_feasible(nodes, pods, a)


def test_static_mask_respected():
    nodes = [
        MakeNode().name("tainted").capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
        .taint("k", "v", "NoSchedule").obj(),
        MakeNode().name("open").capacity({"cpu": "8", "memory": "32Gi", "pods": "20"}).obj(),
    ]
    pods = [MakePod().name(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
    a, _ = solve(nodes, pods)
    assert all(x == 1 for x in a)  # only the untainted node


def test_mixed_request_classes():
    rng = np.random.default_rng(5)
    nodes = [
        MakeNode().name(f"n{i:03}")
        .capacity({"cpu": "16", "memory": "64Gi", "pods": "50"})
        .label("zone", f"z{i % 3}")
        .obj()
        for i in range(32)
    ]
    pods = []
    for i in range(400):
        cpu = int(rng.integers(1, 8)) * 250
        b = MakePod().name(f"p{i:04}").req(
            {"cpu": f"{cpu}m", "memory": f"{int(rng.integers(1, 4))}Gi"}
        ).priority(int(rng.integers(0, 3)))
        if i % 5 == 0:
            b = b.node_selector({"zone": f"z{i % 3}"})
        pods.append(b.obj())
    a, _ = solve(nodes, pods)
    check_feasible(nodes, pods, a)
    assert int((a >= 0).sum()) == 400  # ample capacity
    # selector pods landed in the right zone
    for i in range(0, 400, 5):
        assert int(a[i]) % 3 == i % 3


def test_quality_vs_exact():
    """VERDICT r2 #6: run both solvers on ONE contended workload and bound
    the auction's quality gap against the exact sequential anchor — placed
    count, placed priority mass, and fit-headroom balance must all be
    within a few percent. The auction optimizes a different objective
    (documented divergence, SURVEY §8.4 mode 2); this pins HOW different."""
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig

    rng = np.random.default_rng(11)
    def mk_nodes():
        return [
            MakeNode().name(f"n{i:03}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "30"})
            .obj()
            for i in range(64)
        ]

    pods = []
    for i in range(900):  # ~1.76x cpu oversubscription: real contention
        cpu = int(rng.integers(1, 5)) * 250
        pods.append(
            MakePod().name(f"p{i:04}")
            .req({"cpu": f"{cpu}m", "memory": f"{int(rng.integers(1, 3))}Gi"})
            .priority(int(rng.integers(0, 8)))
            .obj()
        )
    # queue order: the exact scan consumes pods highest-priority first
    # (PrioritySort), which is also the fairest anchor for the comparison
    pods.sort(key=lambda p: -p.effective_priority)

    def run_exact():
        nodes = mk_nodes()
        vocab = ResourceVocab.build(pods, nodes)
        nbatch = build_node_batch(nodes, vocab=vocab)
        pbatch = build_pod_batch(pods, vocab)
        slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
        static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
        solver = ExactSolver(ExactSolverConfig(tie_break="first", group_size=0))
        return solver.solve(nbatch, pbatch, static, None, None, None)

    a_exact = run_exact()
    a_ss, _ = solve(nodes=mk_nodes(), pods=pods)
    check_feasible(mk_nodes(), pods, a_ss)

    prios = np.asarray([p.effective_priority for p in pods])
    placed_e, placed_s = int((a_exact >= 0).sum()), int((a_ss >= 0).sum())
    mass_e = int(prios[np.asarray(a_exact) >= 0].sum())
    mass_s = int(prios[np.asarray(a_ss) >= 0].sum())
    # the auction must stay within 3% of the sequential anchor on both
    # placed count and placed priority mass
    assert placed_s >= 0.97 * placed_e, (placed_s, placed_e)
    assert mass_s >= 0.97 * mass_e, (mass_s, mass_e)

    # SCORE quality (VERDICT r3 #7): the snapshot-headroom objective of
    # the auction's placements must be within 10% of the exact anchor's
    # under the same formula (identical empty nodes here, so the check
    # reduces to placement balance surviving the objective lens; the
    # preloaded heterogeneous shapes run in bench._quality_table on TPU)
    cap_cpu = 8000.0
    cap_mem = 32 * 1024**3
    score = []
    for a in (a_exact, a_ss):
        placed = np.asarray(a) >= 0
        # per-node fill after this solver's own placements
        fill_cpu = np.zeros(64)
        fill_mem = np.zeros(64)
        for i in np.flatnonzero(placed):
            r = pods[i].resource_request()
            fill_cpu[int(a[i])] += r.get("cpu", 0)
            fill_mem[int(a[i])] += r.get("memory", 0)
        frac = (fill_cpu / cap_cpu + fill_mem / cap_mem) / 2.0
        # balance objective: low variance of final fill = higher headroom
        score.append(float(frac.var()))
    # the auction's fill-balance must not be more than 2x worse than the
    # sequential greedy's (both target balance through their scoring)
    assert score[1] <= max(2.0 * score[0], 1e-4), score


def _preloaded_scarce(seed=3, n_nodes=256, n_pods=1200, rc=8):
    """Miniature of the bench quality table's scarce_rc8 shape: unevenly
    preloaded nodes (heterogeneous base scores), big request classes,
    demand > capacity — the regime where a narrow top-T window strands
    capacity on the fullest (lowest-scored) nodes."""
    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.tensorize.schema import NodeBatch, pad_to

    rng = np.random.default_rng(seed)
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    npad = pad_to(n_nodes)
    live = np.arange(npad) < n_nodes
    alloc = np.zeros((3, npad), np.int64)
    alloc[0, :n_nodes] = 16_000
    alloc[1, :n_nodes] = 64 << 30
    load = rng.integers(0, 9, n_nodes)
    used = np.zeros((3, npad), np.int64)
    used[0, :n_nodes] = load * 1_000
    used[1, :n_nodes] = load * (2 << 30)
    cnt = np.zeros(npad, np.int32)
    cnt[:n_nodes] = load
    rc_cpu = rng.integers(24, 33, rc) * 125
    rc_mem = rng.choice([8 << 30], rc)
    rc_of = np.sort(rng.integers(0, rc, n_pods))
    prio = rng.integers(0, 10, n_pods).astype(np.int32)
    order = np.lexsort((rc_of, -prio))
    rc_of, prio = rc_of[order], prio[order]
    rc_req = np.zeros((rc, 3), np.int64)
    rc_req[:, 0], rc_req[:, 1] = rc_cpu, rc_mem

    def node_batch():
        return NodeBatch(
            vocab=vocab, names=[f"n{i}" for i in range(n_nodes)],
            num_nodes=n_nodes, padded=npad,
            allocatable=alloc.copy(), used=used.copy(),
            nonzero_used=used[:2].copy(), pod_count=cnt.copy(),
            max_pods=np.where(live, 110, 0).astype(np.int32),
            valid=live.copy(), schedulable=live.copy(),
        )

    def pod_batch():
        return columnar_pod_batch(
            rc_req[rc_of, 0].copy(), rc_req[rc_of, 1].copy(),
            prio.copy(), vocab,
        )

    base = (
        100.0
        * (
            (alloc[0] - used[0]) / np.maximum(alloc[0], 1)
            + (alloc[1] - used[1]) / np.maximum(alloc[1], 1)
        )
        / 2.0
    ).astype(np.int64)
    return node_batch, pod_batch, base


def test_scarcity_repair_closes_the_gap():
    """SURVEY §8.4 / VERDICT missing #6: under demand > capacity with a
    narrow top-T window, the fullest nodes score lowest, fall outside
    every class's bid window, their prices never escalate, and capacity
    strands (scarce_rc8 placed_ratio was 0.9854 without repair). The
    full-width repair phase must close it: placed_ratio >= 0.995 and
    objective_ratio >= 0.99 against the exact sequential anchor, on the
    same preloaded cluster through both PUBLIC solver entry points."""
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig

    node_batch, pod_batch, base = _preloaded_scarce()
    # top_t=16 of 256 nodes with a tight round budget: the pre-repair
    # stranding regime, scaled down (without repair this config places
    # ~60% — price rotation alone can't explore the window in time)
    cfg = dict(top_t=16, max_rounds=8)
    a_repair = SingleShotSolver(SingleShotConfig(**cfg)).solve(
        node_batch(), pod_batch()
    )
    a_exact = ExactSolver(
        ExactSolverConfig(tie_break="first", group_size=256)
    ).solve(node_batch(), pod_batch())

    def stats(a):
        a = np.asarray(a)
        placed = a >= 0
        return int(placed.sum()), int(base[a[placed]].sum())

    placed_s, obj_s = stats(a_repair)
    placed_e, obj_e = stats(a_exact)
    assert placed_s >= 0.995 * placed_e, (placed_s, placed_e)
    assert obj_s >= 0.99 * obj_e, (obj_s, obj_e)

    # repair OFF reproduces the stranding gap this test guards against —
    # proving the gate above is non-vacuous for this workload
    a_off = SingleShotSolver(
        SingleShotConfig(repair_rounds=0, **cfg)
    ).solve(node_batch(), pod_batch())
    assert int((np.asarray(a_off) >= 0).sum()) < placed_s


def test_pack_objective_consolidates():
    """objective="pack" (the rebalancer's planning posture) with a
    narrow bid window prefers the FULLEST feasible node instead of the
    emptiest — the consolidation force the defragmentation plan needs.
    top_t=1 makes every pod of a class bid the single best node per
    round (wider windows deliberately fan a class out across the
    window — the serving posture)."""
    nodes = [
        MakeNode().name("full").capacity({"cpu": "8", "memory": "32Gi", "pods": "20"}).obj(),
        MakeNode().name("empty").capacity({"cpu": "8", "memory": "32Gi", "pods": "20"}).obj(),
    ]
    vocab = ResourceVocab.build([], nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    # preload "full" to 50% cpu
    nbatch.used[0, 0] = 4000
    pods = [MakePod().name(f"p{i}").req({"cpu": "1"}).obj() for i in range(2)]
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    a = SingleShotSolver(
        SingleShotConfig(objective="pack", top_t=1)
    ).solve(nbatch, pbatch, static)
    assert all(int(x) == 0 for x in a)  # both landed on the fuller node


def test_moderate_scale_host():
    # 2k pods x 512 nodes on CPU: still fast, exercises fan-out + rounds
    nodes = [
        MakeNode().name(f"n{i:04}")
        .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
        .obj()
        for i in range(512)
    ]
    pods = [
        MakePod().name(f"p{i:05}").req({"cpu": "250m", "memory": "512Mi"}).obj()
        for i in range(2000)
    ]
    a, _ = solve(nodes, pods)
    assert int((a >= 0).sum()) == 2000
    check_feasible(nodes, pods, a)
    # balanced-ish spread: no node should hoard
    counts = np.bincount(a, minlength=512)
    assert counts.max() <= 64  # cpu cap per node
