"""KubeSchedulerConfiguration parsing, multi-profile routing, CLI."""

import json
import textwrap

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.config import types as ct
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState

REFERENCE_STYLE_YAML = """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
parallelism: 8
percentageOfNodesToScore: 50
podInitialBackoffSeconds: 2
podMaxBackoffSeconds: 20
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: NodeResourcesFit
        args:
          scoringStrategy:
            type: MostAllocated
            resources:
              - name: cpu
                weight: 2
              - name: memory
                weight: 1
      - name: InterPodAffinity
        args:
          hardPodAffinityWeight: 10
  - schedulerName: batch-scheduler
    plugins:
      score:
        enabled:
          - name: TaintToleration
            weight: 5
        disabled:
          - name: ImageLocality
extenders:
  - urlPrefix: http://127.0.0.1:10259
    filterVerb: filter
    prioritizeVerb: prioritize
    weight: 2
    nodeCacheCapable: true
    ignorable: true
tpuSolver:
  batchSize: 2048
  tieBreak: first
"""


def test_reference_style_yaml_parses():
    cfg = ct.load(REFERENCE_STYLE_YAML)
    assert cfg.parallelism == 8
    assert cfg.pod_initial_backoff_seconds == 2
    # percentageOfNodesToScore != 0/100 -> parsed with a warning
    assert any("percentageOfNodesToScore" in w for w in cfg.warnings)
    assert len(cfg.profiles) == 2
    p0 = cfg.profile_for("default-scheduler")
    assert p0.scoring_strategy.type == "MostAllocated"
    assert p0.hard_pod_affinity_weight == 10
    p1 = cfg.profile_for("batch-scheduler")
    assert p1.score_weights["TaintToleration"] == 5
    assert p1.score_weights["ImageLocality"] == 0
    assert cfg.extenders[0].node_cache_capable
    assert cfg.tpu_solver.batch_size == 2048
    assert cfg.tpu_solver.tie_break == "first"


def test_duplicate_profile_rejected():
    import pytest

    bad = {
        "profiles": [
            {"schedulerName": "x"},
            {"schedulerName": "x"},
        ]
    }
    with pytest.raises(ValueError):
        ct.load(bad)


def test_scheduler_config_bridge():
    cfg = ct.load(REFERENCE_STYLE_YAML)
    sc = ct.scheduler_config(cfg)
    assert sc.batch_size == 2048
    # every profile becomes a routing entry
    assert set(sc.profiles) == {"default-scheduler", "batch-scheduler"}
    batch = sc.profiles["batch-scheduler"]
    assert batch.taint_weight == 5
    assert batch.image_weight == 0
    assert batch.tie_break == "first"
    assert sc.profiles["default-scheduler"].scoring_strategy == "MostAllocated"


def test_multi_profile_routing():
    cs = ClusterState()
    for i in range(4):
        cs.create_node(
            MakeNode().name(f"n{i}").capacity(
                {"cpu": "8", "memory": "32Gi", "pods": "20"}
            ).obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16,
            profiles={
                "default-scheduler": ExactSolverConfig(tie_break="first"),
                "batch-scheduler": ExactSolverConfig(tie_break="first"),
            },
        ),
    )
    cs.create_pod(MakePod().name("a").req({"cpu": "1"}).obj())
    cs.create_pod(
        MakePod().name("b").scheduler_name("batch-scheduler").req({"cpu": "1"}).obj()
    )
    # a pod for an unknown scheduler is ignored entirely
    cs.create_pod(
        MakePod().name("ghost").scheduler_name("other").req({"cpu": "1"}).obj()
    )
    r = sched.schedule_batch()
    scheduled = {k for k, _ in r.scheduled}
    assert scheduled == {"default/a", "default/b"}
    assert sched.pending == 0  # ghost never queued


def test_node_update_precheck_gates_wakeups():
    cs = ClusterState()
    node = MakeNode().name("n0").capacity({"cpu": "1", "memory": "4Gi", "pods": "10"}).obj()
    cs.create_node(node)
    sched = Scheduler(cs, SchedulerConfig(batch_size=4))
    cs.create_pod(MakePod().name("big").req({"cpu": "4"}).obj())
    r = sched.schedule_batch()
    assert r.unschedulable == ["default/big"]
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # irrelevant node update (no allocatable/label/taint change): stays parked
    cs.update_node(cs.get_node("n0"))
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # allocatable grows: pod moves to backoff/active
    bigger = MakeNode().name("n0").capacity({"cpu": "8", "memory": "4Gi", "pods": "10"}).obj()
    cs.update_node(bigger)
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 0
    assert counts["active"] + counts["backoff"] == 1


def test_most_allocated_strategy_parity():
    """MostAllocated (bin-packing) through solver + oracle: pods pile onto
    the already-loaded node instead of spreading."""
    from kubernetes_tpu.ops.oracle.profile import (
        FullOracle,
        ProfileWeights,
        make_oracle_nodes,
    )
    from kubernetes_tpu.tensorize.schema import (
        ResourceVocab,
        build_node_batch,
        build_pod_batch,
    )
    from kubernetes_tpu.solver.exact import ExactSolver

    nodes = [
        MakeNode().name(f"n{i}").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "20"}
        ).obj()
        for i in range(3)
    ]
    seed = MakePod().name("seed").node("n0").req({"cpu": "2", "memory": "4Gi"}).obj()
    pods = [
        MakePod().name(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj()
        for i in range(4)
    ]
    vocab = ResourceVocab.build(pods + [seed], nodes)
    nbatch = build_node_batch(nodes, {"n0": [seed]}, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    solver = ExactSolver(
        ExactSolverConfig(tie_break="first", scoring_strategy="MostAllocated")
    )
    a = solver.solve(nbatch, pbatch)
    assert all(x == 0 for x in a)  # packs onto the loaded node
    oracle = FullOracle(
        make_oracle_nodes(nodes, {"n0": [seed]}),
        ProfileWeights(scoring_strategy="MostAllocated"),
    )
    names = [nbatch.names[x] for x in a]
    errors = oracle.validate_assignments(pods, list(a), names=names)
    assert not errors, errors[:3]


def test_cli_config_command(tmp_path, capsys):
    from kubernetes_tpu.cli import main

    p = tmp_path / "cfg.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    rc = main(["--config", str(p), "config"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["profiles"][0]["scoringStrategy"] == "MostAllocated"
    assert out["tpuSolver"]["batchSize"] == 2048


def test_cli_perf_command(tmp_path, capsys):
    from kubernetes_tpu.cli import main

    wl = tmp_path / "wl.yaml"
    wl.write_text(
        textwrap.dedent(
            """
            - name: Mini
              workloadTemplate:
                - {opcode: createNodes, count: 4}
                - {opcode: createPods, count: 8, collectMetrics: true}
                - {opcode: barrier}
              workloads:
                - name: only
                  params: {}
            """
        )
    )
    rc = main(["perf", str(wl)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["scheduled"] == 8
