"""Grouped fast-path solver (§8.4 batched variant) ≡ per-pod sequential scan.

The grouped solver must be indistinguishable from the ungrouped scan with
tie_break="first" (deterministic): same assignments pod-for-pod, on
workloads mixing uniform runs (deployment replicas) with odd one-off pods,
taints, node affinity, host ports, and near-capacity nodes — the cases
that stress the fast path's cap precomputation and its per-iteration
re-normalization of TaintToleration/NodeAffinity scores.
"""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)

MB = 1024 * 1024
GB = 1024 * MB


def solve(nodes, pods, group_size):
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    solver = ExactSolver(
        ExactSolverConfig(tie_break="first", group_size=group_size)
    )
    return solver.solve(nbatch, pbatch, static, ports)


def mk_nodes(n, rng, taint_every=0, label_every=0):
    nodes = []
    for i in range(n):
        b = (
            MakeNode()
            .name(f"node-{i:03}")
            .capacity(
                {
                    "cpu": str(int(rng.integers(2, 9))),
                    "memory": f"{int(rng.integers(4, 33))}Gi",
                    "pods": str(int(rng.integers(3, 20))),
                }
            )
        )
        if taint_every and i % taint_every == 0:
            b = b.taint("dedicated", "gpu", "NoSchedule")
        if label_every and i % label_every == 0:
            b = b.label("disk", "ssd")
        nodes.append(b.obj())
    return nodes


def mk_replica_run(name, count, cpu_m, mem_mb, *, port=0, affinity=False,
                   tolerate=False):
    pods = []
    for i in range(count):
        b = MakePod().name(f"{name}-{i:03}").req(
            {"cpu": f"{cpu_m}m", "memory": f"{mem_mb}Mi"}
        )
        if port:
            b = b.host_port(port)
        if affinity:
            b = b.node_affinity_in("disk", ["ssd"])
        if tolerate:
            b = b.toleration("dedicated", "gpu", "NoSchedule")
        pods.append(b.obj())
    return pods


@pytest.mark.parametrize("group", [4, 8])
def test_uniform_runs_match_sequential(group):
    rng = np.random.default_rng(7)
    nodes = mk_nodes(24, rng, taint_every=5, label_every=3)
    pods = (
        mk_replica_run("web", 40, 250, 512)
        + mk_replica_run("db", 17, 1000, 2048, affinity=True)
        + mk_replica_run("agent", 23, 100, 128, tolerate=True)
    )
    seq = solve(nodes, pods, group_size=0)
    grp = solve(nodes, pods, group_size=group)
    np.testing.assert_array_equal(seq, grp)


@pytest.mark.parametrize("group", [4, 8])
def test_mixed_and_oneoff_pods(group):
    """Interleave uniform runs with distinct pods so chunks alternate
    between the fast and fallback branches."""
    rng = np.random.default_rng(11)
    nodes = mk_nodes(16, rng, label_every=4)
    pods = []
    for i in range(60):
        if i % 7 == 0:
            pods.append(
                MakePod()
                .name(f"odd-{i:03}")
                .req(
                    {
                        "cpu": f"{int(rng.integers(1, 16)) * 50}m",
                        "memory": f"{int(rng.integers(1, 9)) * 256}Mi",
                    }
                )
                .obj()
            )
        else:
            pods.append(
                MakePod().name(f"run-{i:03}").req(
                    {"cpu": "200m", "memory": "256Mi"}
                ).obj()
            )
    seq = solve(nodes, pods, group_size=0)
    grp = solve(nodes, pods, group_size=group)
    np.testing.assert_array_equal(seq, grp)


def test_host_ports_cap_one_per_node():
    """Identical pods with a host port: at most one per node, and the fast
    path's cap logic must agree with sequential port-occupancy updates."""
    rng = np.random.default_rng(3)
    nodes = mk_nodes(6, rng)
    pods = mk_replica_run("lb", 10, 100, 128, port=8080)
    seq = solve(nodes, pods, group_size=0)
    grp = solve(nodes, pods, group_size=4)
    np.testing.assert_array_equal(seq, grp)
    placed = [a for a in grp if a >= 0]
    assert len(placed) == len(set(placed)) == 6  # one per node, 4 overflow


def test_capacity_saturation_tail_unschedulable():
    """More identical pods than total capacity: the tail must come back -1
    in both paths (an infeasible identical pod stays infeasible)."""
    rng = np.random.default_rng(5)
    nodes = [
        MakeNode().name(f"n-{i}").capacity(
            {"cpu": "1", "memory": "1Gi", "pods": "3"}
        ).obj()
        for i in range(3)
    ]
    pods = mk_replica_run("big", 20, 300, 200)
    seq = solve(nodes, pods, group_size=0)
    grp = solve(nodes, pods, group_size=4)
    np.testing.assert_array_equal(seq, grp)
    assert (np.asarray(grp) == -1).sum() > 0


def test_random_tiebreak_multiplace_is_sequentially_valid():
    """tie_break=random engages the multi-placement path; its picks must
    each lie in the oracle's tie set given the pods placed before them —
    the §8.8 parity definition for the randomized tie-break."""
    from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes

    rng = np.random.default_rng(21)
    nodes = mk_nodes(20, rng, taint_every=4, label_every=3)
    pods = (
        mk_replica_run("a", 48, 250, 512)
        + mk_replica_run("b", 30, 500, 1024, tolerate=True)
    )
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    solver = ExactSolver(
        ExactSolverConfig(tie_break="random", group_size=8)
    )
    assignments = solver.solve(nbatch, pbatch, static, ports)
    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    errors = oracle.validate_assignments(pods, list(assignments), names=names)
    assert not errors, "\n".join(errors[:5])


def test_random_fuzz_many_seeds():
    for seed in range(6):
        rng = np.random.default_rng(100 + seed)
        nodes = mk_nodes(int(rng.integers(4, 20)), rng,
                         taint_every=int(rng.integers(0, 4)),
                         label_every=int(rng.integers(0, 4)))
        pods = []
        n_runs = int(rng.integers(1, 5))
        for r in range(n_runs):
            cnt = int(rng.integers(1, 25))
            pods += mk_replica_run(
                f"r{seed}-{r}", cnt,
                int(rng.integers(1, 10)) * 100,
                int(rng.integers(1, 8)) * 256,
                tolerate=bool(rng.integers(0, 2)),
            )
        seq = solve(nodes, pods, group_size=0)
        grp = solve(nodes, pods, group_size=8)
        np.testing.assert_array_equal(seq, grp)


def test_random_mode_distribution_divergence_bounded():
    """VERDICT r3 weak #9: random-mode grouped multi-placement produces a
    DIFFERENT placement distribution than the per-pod scan for the same
    seed (documented in ExactSolverConfig.group_size); this quantifies
    the drift instead of just asserting validity. Over many seeds, the
    per-node placement marginals of the two modes must agree within
    total-variation 0.1 (and each must sit within TV 0.1 of the uniform
    tie-set distribution), and their balance profiles (max pods on any
    node) must agree in expectation within 1."""
    import numpy as np

    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab, pad_to

    n_nodes, n_pods, seeds = 16, 32, 60
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    npad = pad_to(n_nodes)

    def fresh_nodes():
        alloc = np.zeros((3, npad), np.int64)
        alloc[0, :n_nodes] = 16_000
        alloc[1, :n_nodes] = 64 << 30
        return NodeBatch(
            vocab=vocab, names=[f"n{i}" for i in range(n_nodes)],
            num_nodes=n_nodes, padded=npad, allocatable=alloc,
            used=np.zeros((3, npad), np.int64),
            nonzero_used=np.zeros((2, npad), np.int64),
            pod_count=np.zeros(npad, np.int32),
            max_pods=np.where(np.arange(npad) < n_nodes, 110, 0).astype(np.int32),
            valid=np.arange(npad) < n_nodes,
            schedulable=np.arange(npad) < n_nodes,
        )

    cpu = np.full(n_pods, 1000, np.int64)
    mem = np.full(n_pods, 2 << 30, np.int64)

    def marginals(group):
        counts = np.zeros(n_nodes)
        max_loads = []
        for seed in range(seeds):
            solver = ExactSolver(
                ExactSolverConfig(
                    tie_break="random", seed=seed, group_size=group
                )
            )
            a = solver.solve(
                fresh_nodes(), columnar_pod_batch(cpu, mem, None, vocab)
            )
            assert (a >= 0).all()
            per_node = np.bincount(a, minlength=n_nodes)
            counts += per_node
            max_loads.append(per_node.max())
        return counts / counts.sum(), float(np.mean(max_loads))

    m_scan, ml_scan = marginals(0)       # per-pod scan
    m_grouped, ml_grouped = marginals(16)  # grouped multi-placement
    tv = 0.5 * np.abs(m_scan - m_grouped).sum()
    assert tv < 0.1, f"node-marginal TV distance {tv:.3f}"
    uniform = np.full(n_nodes, 1.0 / n_nodes)
    for name, m in (("scan", m_scan), ("grouped", m_grouped)):
        tvu = 0.5 * np.abs(m - uniform).sum()
        assert tvu < 0.1, f"{name} marginal vs uniform TV {tvu:.3f}"
    assert abs(ml_scan - ml_grouped) <= 1.0, (ml_scan, ml_grouped)


# -- compact wire mode (one representative row per chunk) --------------------


def _solve_full(nodes, pods, group, *, compact, spread=False, tie="first",
                seed=0):
    """Full tensorizer pipeline solve with the compact_wire knob exposed,
    returning (assignments, solver) so tests can assert which wire path
    actually ran."""
    from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
    from kubernetes_tpu.tensorize.spread import build_spread_tensors

    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    spr = ipa = None
    if spread:
        spr = build_spread_tensors(
            pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded,
            static.c_pad,
        )
        ipa = build_interpod_tensors(
            pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded,
            static.c_pad,
        )
    solver = ExactSolver(
        ExactSolverConfig(
            tie_break=tie, seed=seed, group_size=group, compact_wire=compact
        )
    )
    return solver.solve(nbatch, pbatch, static, ports, spr, ipa), solver


def test_compact_wire_bit_identical_uniform():
    """Uniform replica runs: the compact upload (one row per chunk + vcnt)
    must engage and produce bit-identical assignments to the full [P, *]
    upload, including the tail chunk whose validity is a partial prefix."""
    rng = np.random.default_rng(13)
    nodes = mk_nodes(12, rng, taint_every=4)
    pods = mk_replica_run("web", 42, 250, 512)  # 42 % 8 != 0: partial tail
    a_full, s_full = _solve_full(nodes, pods, 8, compact=False)
    a_comp, s_comp = _solve_full(nodes, pods, 8, compact=True)
    np.testing.assert_array_equal(a_full, a_comp)
    assert s_comp.dispatch_counts.get("compact_batches", 0) == 1
    assert s_full.dispatch_counts.get("compact_batches", 0) == 0


def test_compact_wire_random_mode_same_seed():
    """Random tie-break: same seed, same chunk kinds => the compact path
    consumes identical PRNG draws, so results stay bit-identical."""
    rng = np.random.default_rng(17)
    nodes = mk_nodes(10, rng)
    pods = mk_replica_run("app", 32, 300, 256)
    a_full, _ = _solve_full(nodes, pods, 8, compact=False, tie="random", seed=5)
    a_comp, s = _solve_full(nodes, pods, 8, compact=True, tie="random", seed=5)
    np.testing.assert_array_equal(a_full, a_comp)
    assert s.dispatch_counts.get("compact_batches", 0) == 1


def test_compact_wire_slow_chunk_broadcast_replay():
    """Uniform pods whose shape defeats the quota fast paths (hard zone
    spread + a preferred node affinity => nonzero preference rows => kind
    0) must replay the broadcast representative through the full per-pod
    pipeline bit-identically to the full upload AND to the ungrouped
    scan."""
    rng = np.random.default_rng(19)
    nodes = []
    for i in range(9):
        nodes.append(
            MakeNode()
            .name(f"zn-{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "30"})
            .label("topology.kubernetes.io/zone", f"z{i % 3}")
            .label("disk", "ssd" if i % 2 == 0 else "hdd")
            .obj()
        )
    pods = []
    # 26 % 8 != 0: the tail kind-0 chunk has vc < group, exercising the
    # compact slow branch's reconstructed pod_valid = iota < vc masking
    # (padding rows of a compact slow chunk carry live representative data)
    for i in range(26):
        pods.append(
            MakePod()
            .name(f"sp-{i:02}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .label("app", "sp")
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule",
                {"app": "sp"},
            )
            .preferred_node_affinity(5, "disk", ["ssd"])
            .obj()
        )
    a_scan, _ = _solve_full(nodes, pods, 0, compact=False, spread=True)
    a_full, s_full = _solve_full(nodes, pods, 8, compact=False, spread=True)
    a_comp, s_comp = _solve_full(nodes, pods, 8, compact=True, spread=True)
    np.testing.assert_array_equal(a_scan, a_full)
    np.testing.assert_array_equal(a_full, a_comp)
    assert s_comp.dispatch_counts.get("kind0", 0) > 0  # slow chunks ran
    assert s_comp.dispatch_counts.get("compact_batches", 0) == 1


def test_compact_wire_falls_back_on_mixed_rows():
    """A chunk with two different pod shapes is not row-uniform: compact
    must NOT engage, and results must still match the ungrouped scan."""
    rng = np.random.default_rng(23)
    nodes = mk_nodes(8, rng)
    pods = mk_replica_run("a", 12, 200, 256) + mk_replica_run(
        "b", 12, 400, 512
    )
    order = rng.permutation(len(pods))
    pods = [pods[i] for i in order]
    a_scan, _ = _solve_full(nodes, pods, 0, compact=False)
    a_grp, s = _solve_full(nodes, pods, 8, compact=True)
    np.testing.assert_array_equal(a_scan, a_grp)
    assert s.dispatch_counts.get("compact_batches", 0) == 0


# -- hypothesis property: compact wire ≡ full upload ------------------------

from _hypothesis_compat import given, settings, st


@settings(max_examples=12, deadline=None)
@given(
    seed=st.integers(0, 10_000),
    n_nodes=st.integers(3, 14),
    runs=st.lists(
        st.tuples(
            st.integers(1, 18),  # replicas
            st.integers(1, 8),  # cpu units of 100m
            st.integers(1, 6),  # memory units of 256Mi
            st.booleans(),  # tolerate the taint
        ),
        min_size=1,
        max_size=3,
    ),
    group=st.sampled_from([4, 8]),
    tie=st.sampled_from(["first", "random"]),
)
def test_compact_wire_equivalence_property(seed, n_nodes, runs, group, tie):
    """For ANY workload of uniform replica runs (the compact-eligible
    family), the compact upload must be bit-identical to the full [P, *]
    upload under both tie-break modes — including partial tail chunks and
    mixed-run batches that fall back to the full path."""
    rng = np.random.default_rng(seed)
    nodes = mk_nodes(n_nodes, rng, taint_every=3)
    pods = []
    for ri, (cnt, cpu_u, mem_u, tol) in enumerate(runs):
        pods += mk_replica_run(
            f"r{ri}", cnt, cpu_u * 100, mem_u * 256, tolerate=tol
        )
    a_full, _ = _solve_full(nodes, pods, group, compact=False, tie=tie,
                            seed=seed)
    a_comp, _ = _solve_full(nodes, pods, group, compact=True, tie=tie,
                            seed=seed)
    np.testing.assert_array_equal(a_full, a_comp)
