"""Post-packed-download sweep: group size x pod padding at the north star."""
import time

import jax

jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache()

from kubernetes_tpu.server.bulk import columnar_pod_batch
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab, pad_to

NS_NODES = 10_240
NS_PODS = 51_200
vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))


def fresh_batch():
    npad = pad_to(NS_NODES)
    alloc = np.zeros((3, npad), dtype=np.int64)
    alloc[0, :NS_NODES] = 16_000
    alloc[1, :NS_NODES] = 64 << 30
    live = np.arange(npad) < NS_NODES
    used = np.zeros((3, npad), np.int64)
    return NodeBatch(
        vocab=vocab,
        names=[f"n{i}" for i in range(NS_NODES)],
        num_nodes=NS_NODES,
        padded=npad,
        allocatable=alloc,
        used=used,
        nonzero_used=used[:2].copy(),
        pod_count=np.zeros(npad, np.int32),
        max_pods=np.where(live, 110, 0).astype(np.int32),
        valid=live,
        schedulable=live.copy(),
    )


def pb_exact_pad():
    """PodBatch padded to exactly NS_PODS (multiple of every group tested)."""
    pb = columnar_pod_batch(
        np.full(NS_PODS, 1000, np.int64),
        np.full(NS_PODS, 2 << 30, np.int64),
        None,
        vocab,
    )
    import dataclasses

    return dataclasses.replace(
        pb,
        padded=NS_PODS,
        req=pb.req[:NS_PODS],
        req_mask=pb.req_mask[:NS_PODS],
        nonzero_req=pb.nonzero_req[:NS_PODS],
        valid=pb.valid[:NS_PODS],
        feasible_static=pb.feasible_static[:NS_PODS],
        priority=pb.priority[:NS_PODS],
    )


_ = np.asarray(jax.jit(lambda x: x * 2)(jnp.arange(8)))  # sync mode

for pad_mode in ("pow2", "exact"):
    for g in (1024, 2048, 4096):
        if pad_mode == "exact" and NS_PODS % g:
            continue  # grouped_eligible needs pod_pad % group == 0
        pb = (
            columnar_pod_batch(
                np.full(NS_PODS, 1000, np.int64),
                np.full(NS_PODS, 2 << 30, np.int64),
                None,
                vocab,
            )
            if pad_mode == "pow2"
            else pb_exact_pad()
        )
        solver = ExactSolver(
            ExactSolverConfig(tie_break="random", group_size=g)
        )
        t0 = time.perf_counter()
        a = solver.solve(fresh_batch(), pb)
        warm = time.perf_counter() - t0
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            a = solver.solve(fresh_batch(), pb)
            times.append(round(time.perf_counter() - t0, 3))
        placed = int((a >= 0).sum())
        assert placed == NS_PODS, f"{placed}/{NS_PODS}"
        print(
            f"pad={pad_mode:5s} g={g:4d} warm={warm:5.1f}s times={times} "
            f"best={min(times):.3f} med={sorted(times)[2]:.3f}",
            flush=True,
        )
