"""volumebinding Reserve/PreBind (AssumePodVolumes/BindPodVolumes) and the
feature-gate map — the last two missing components from VERDICT r2 (#9)."""

import pytest

from kubernetes_tpu.api.objects import (
    PersistentVolume,
    PersistentVolumeClaim,
)
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils.featuregate import FeatureGates

GB = 1024**3
ZONE = "topology.kubernetes.io/zone"


def _cluster_with_pvs():
    cs = ClusterState()
    for i, zone in enumerate(["east", "east", "west"]):
        cs.create_node(
            MakeNode().name(f"n{i}")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
            .label(ZONE, zone).obj()
        )
    # two PVs zoned east, one west; sizes differ so smallest-fit is visible
    for name, zone, size in (
        ("pv-small", "east", 5 * GB),
        ("pv-big", "east", 50 * GB),
        ("pv-west", "west", 20 * GB),
    ):
        cs.create_pv(
            PersistentVolume(
                name=name,
                labels={ZONE: zone},
                capacity_bytes=size,
                storage_class="standard",
            )
        )
    return cs


def _sched(cs, gates=None):
    return Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first"), feature_gates=gates
        ),
        clock=FakeClock(),
    )


def test_wffc_claim_binds_at_schedule_time():
    """WaitForFirstConsumer claim: passes Filter unbound, binds at
    Reserve/PreBind on the chosen node — the smallest adequate PV."""
    cs = _cluster_with_pvs()
    cs.create_pvc(
        PersistentVolumeClaim(
            name="data", storage_class="standard", request_bytes=2 * GB,
            wait_for_first_consumer=True,
        )
    )
    sched = _sched(cs)
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).pvc("data").obj())
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/p")
    pvc = {c.key: c for c in cs.list_pvcs()}["default/data"]
    assert pvc.volume_name == "pv-small"  # smallest adequate
    pv = {v.name: v for v in cs.list_pvs()}["pv-small"]
    assert pv.claim_ref == "default/data"


def test_reserve_failure_requeues_and_rolls_back():
    """A WFFC claim too big for any PV: Filter passes (deferred), Reserve
    fails, the pod requeues, and nothing stays bound."""
    cs = _cluster_with_pvs()
    cs.create_pvc(
        PersistentVolumeClaim(
            name="huge", storage_class="standard", request_bytes=500 * GB,
            wait_for_first_consumer=True,
        )
    )
    sched = _sched(cs)
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).pvc("huge").obj())
    r = sched.schedule_batch()
    assert r.bind_failures and "no matching PersistentVolume" in r.bind_failures[0][1]
    assert not r.scheduled
    assert all(not v.claim_ref for v in cs.list_pvs())
    assert all(not c.volume_name for c in cs.list_pvcs())


def test_two_claims_get_distinct_pvs():
    cs = _cluster_with_pvs()
    for name in ("a", "b"):
        cs.create_pvc(
            PersistentVolumeClaim(
                name=name, storage_class="standard", request_bytes=2 * GB,
                wait_for_first_consumer=True,
            )
        )
    sched = _sched(cs)
    cs.create_pod(
        MakePod().name("p").req({"cpu": "1"}).pvc("a").pvc("b").obj()
    )
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/p")
    bound = {c.name: c.volume_name for c in cs.list_pvcs()}
    assert bound["a"] and bound["b"] and bound["a"] != bound["b"]


# -- feature gates -----------------------------------------------------------


def test_feature_gate_parsing():
    fg = FeatureGates.parse("SchedulerQueueingHints=false")
    assert not fg.enabled("SchedulerQueueingHints")
    assert fg.enabled("PodSchedulingReadiness")  # default
    with pytest.raises(ValueError):
        FeatureGates.parse("NoSuchGate=true")
    with pytest.raises(ValueError):
        FeatureGates.parse("SchedulerQueueingHints=maybe")
    # DRA is implemented (round 4): enabling the gate is no longer a
    # warned-but-ignored flag
    fg = FeatureGates.parse("DynamicResourceAllocation=true")
    assert fg.enabled("DynamicResourceAllocation") and not fg.warnings


def test_pod_scheduling_readiness_gate_off_ignores_gates():
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
    )
    gated_pod = MakePod().name("g").req({"cpu": "1"}).scheduling_gates(["wait"]).obj()

    # gate ON (default): pod parks as gated
    s1 = _sched(cs)
    cs.create_pod(gated_pod)
    r = s1.schedule_batch()
    assert not r.scheduled
    assert s1.queue.pending_counts()["gated"] == 1
    cs.delete_pod("default", "g")

    # gate OFF: schedulingGates ignored, pod schedules
    cs2 = ClusterState()
    cs2.create_node(
        MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
    )
    s2 = _sched(cs2, gates=FeatureGates.parse("PodSchedulingReadiness=false"))
    cs2.create_pod(
        MakePod().name("g").req({"cpu": "1"}).scheduling_gates(["wait"]).obj()
    )
    r = s2.schedule_batch()
    assert dict(r.scheduled).get("default/g") == "n"


def test_queueing_hints_gate_off_moves_everything():
    """With SchedulerQueueingHints=false, a cpu-only node update wakes even
    a memory-blocked pod (the pre-hints behavior)."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    sched = _sched(cs, gates=FeatureGates.parse("SchedulerQueueingHints=false"))
    cs.create_pod(
        MakePod().name("mem-blocked").req({"cpu": "1", "memory": "64Gi"}).obj()
    )
    sched.schedule_batch()
    assert sched.queue.pending_counts()["unschedulable"] == 1
    cs.update_node(
        MakeNode().name("n").capacity({"cpu": "16", "memory": "4Gi", "pods": "10"}).obj()
    )
    # gate off: moved despite not fitting
    assert sched.queue.pending_counts()["unschedulable"] == 0
