"""Device kernels for the static plugins' in-scan pieces.

The static plugin *semantics* are precompiled host-side into per-class
tensors (tensorize/plugins.py); what runs on device per scan step is:
- a row gather (class -> [N] mask / raw scores),
- DefaultNormalizeScore over the feasible set (normalize_score), and
- the NodePorts occupancy test (ports_conflict_mask) + occupancy update.

Reference:
- helper/normalize_score.go#DefaultNormalizeScore
- framework/types.go#HostPortInfo.CheckConflict (pairwise conflict relation
  precompiled into pod_conflict[V]; on device it reduces to "is any
  conflicting vocab slot occupied", an int matvec that XLA fuses)
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100


def normalize_score(raw, mask, reverse: bool):
    """DefaultNormalizeScore over the feasible (masked) set.

    raw: [N] int32 non-negative, mask: [N] bool. Returns [N] int32; values on
    masked-out lanes are unspecified (caller masks the total).
    """
    s = jnp.where(mask, raw, 0).astype(jnp.int32)
    max_count = jnp.max(s)
    # int32 `//` measures FASTER than the float-estimate trick on this VPU
    # (the reverse holds for int64 — see ops/fastmath.py)
    scaled = MAX_NODE_SCORE * s // jnp.maximum(max_count, 1)
    if reverse:
        # maxCount == 0 => all scores become maxPriority
        return jnp.where(max_count > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE)
    return jnp.where(max_count > 0, scaled, 0)


def ports_conflict_mask(pod_conflict_row, port_used):
    """True where the node has an occupied port slot conflicting with the pod.

    pod_conflict_row: [V] bool, port_used: [V, N] int32 occupancy counts.
    """
    busy = (port_used > 0).astype(jnp.int32)
    conflicts = pod_conflict_row.astype(jnp.int32) @ busy  # [N]
    return conflicts > 0
