"""CLI: ``python -m kubernetes_tpu.analysis [options] [paths...]``.

Exit status 0 when every finding is suppressed (with a reason) and
every enabled gate holds, 1 otherwise — scripts/lint.py and the tier-1
gate both key on this. 2 means the invocation itself was wrong (bad
path).

Gates and artifacts beyond the finding scan:

- ``--sarif FILE``      write the findings as SARIF 2.1.0 (CI artifact)
- ``--ratchet``         enforce the suppression-debt baseline
- ``--write-baseline``  regenerate analysis/suppression_baseline.json
- ``--check-lock-order`` fail if docs/LOCK_ORDER.md drifted from the
  computed lock graph
- ``--write-lock-order`` regenerate docs/LOCK_ORDER.md
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (
    ALL_PASSES,
    ALL_PROJECT_PASSES,
    analyze_project,
    build_project,
    default_context,
    load_modules,
)
from .passes.lockorder import lock_order_markdown
from .ratchet import (
    BASELINE_PATH,
    check_ratchet,
    count_suppressions,
    load_baseline,
    render_baseline,
)
from .sarif import render_sarif

LOCK_ORDER_PATH = (
    Path(__file__).resolve().parents[2] / "docs" / "LOCK_ORDER.md"
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="Tracer-safety & lock-discipline static analyzer.",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories (default: the kubernetes_tpu package)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings (including suppressed) as a JSON array",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings in text mode",
    )
    parser.add_argument(
        "--sarif", metavar="FILE",
        help="write findings as SARIF 2.1.0 ('-' for stdout)",
    )
    parser.add_argument(
        "--ratchet", action="store_true",
        help="enforce the suppression-debt baseline "
        f"({BASELINE_PATH.name})",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the suppression-debt baseline file",
    )
    parser.add_argument(
        "--check-lock-order", action="store_true",
        help="fail if docs/LOCK_ORDER.md drifted from the computed "
        "lock graph",
    )
    parser.add_argument(
        "--write-lock-order", action="store_true",
        help="regenerate docs/LOCK_ORDER.md from the computed lock graph",
    )
    args = parser.parse_args(argv)

    ctx = default_context()
    try:
        modules, broken = load_modules(args.paths or None)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    findings = analyze_project(modules, ctx=ctx)
    findings.extend(broken)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    failures: list[str] = []

    if args.sarif:
        text = render_sarif(findings)
        if args.sarif == "-":
            print(text)
        else:
            Path(args.sarif).write_text(text + "\n")

    if args.write_baseline:
        BASELINE_PATH.write_text(
            render_baseline(count_suppressions(modules))
        )
        print(f"wrote {BASELINE_PATH}", file=sys.stderr)
    elif args.ratchet:
        failures.extend(
            check_ratchet(count_suppressions(modules), load_baseline())
        )

    if args.write_lock_order or args.check_lock_order:
        project = build_project(modules, ctx)
        artifact = lock_order_markdown(project)
        if args.write_lock_order:
            LOCK_ORDER_PATH.write_text(artifact)
            print(f"wrote {LOCK_ORDER_PATH}", file=sys.stderr)
        elif args.check_lock_order:
            committed = (
                LOCK_ORDER_PATH.read_text()
                if LOCK_ORDER_PATH.exists()
                else ""
            )
            if committed != artifact:
                failures.append(
                    "docs/LOCK_ORDER.md drifted from the computed lock "
                    "graph — regenerate: python -m kubernetes_tpu."
                    "analysis --write-lock-order"
                )

    if args.as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
        for msg in failures:
            print(f"GATE: {msg}", file=sys.stderr)
    else:
        shown = findings if args.show_suppressed else active
        for f in shown:
            print(f.render())
        for msg in failures:
            print(f"GATE: {msg}")
        rules = ", ".join(
            c.rule for c in ALL_PASSES + ALL_PROJECT_PASSES
        )
        print(
            f"{len(active)} finding(s), {len(suppressed)} suppressed "
            f"(passes: {rules})"
        )
    return 1 if (active or failures) else 0


if __name__ == "__main__":
    sys.exit(main())
