"""Grouped quota fast paths for spread/anti workloads (kind 2/3 chunks in
solver/exact._solve_grouped): deterministic mode must be bit-identical to
the ungrouped scan; random mode must be sequentially valid (oracle
replay) and respect the workload invariants."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)
from kubernetes_tpu.tensorize.spread import build_spread_tensors

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"
GROUP = 16


def mk_nodes(n):
    return [
        MakeNode()
        .name(f"n-{i:04}")
        .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
        .label(ZONE, f"z{i % 3}")
        .label(HOST, f"n-{i:04}")
        .obj()
        for i in range(n)
    ]


def mk_pods(n, kind):
    out = []
    for i in range(n):
        b = (
            MakePod()
            .name(f"p-{i:04}")
            .label("app", kind)
            .req({"cpu": "250m", "memory": "512Mi"})
        )
        if kind == "spread":
            b = b.spread_constraint(1, ZONE, "DoNotSchedule", {"app": kind})
        elif kind == "anti":
            b = b.pod_anti_affinity(HOST, {"app": kind})
        out.append(b.obj())
    return out


def solve(nodes, pods, tie_break, group, seed=3):
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    # grouped dispatch needs pod_pad % group == 0
    pad = ((len(pods) + GROUP - 1) // GROUP) * GROUP
    pbatch = build_pod_batch(pods, vocab, pad=pad)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    solver = ExactSolver(
        ExactSolverConfig(tie_break=tie_break, group_size=group, seed=seed)
    )
    return (
        solver.solve(nbatch, pbatch, static, ports, spread, interpod),
        nbatch,
    )


def test_chunk_kinds_classification():
    nodes = mk_nodes(32)
    pods = mk_pods(GROUP, "spread") + mk_pods(GROUP, "anti") + mk_pods(GROUP, "plain")
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab, pad=3 * GROUP)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    kinds = ExactSolver._chunk_kinds(
        pbatch, static, ports, spread, interpod, GROUP, True, True
    )
    assert list(kinds) == [2, 3, 1]


def test_spread_deterministic_grouped_equals_ungrouped():
    nodes = mk_nodes(24)
    pods = mk_pods(48, "spread")
    a_g, nb = solve(nodes, pods, "first", GROUP)
    a_u, _ = solve(nodes, pods, "first", 0)
    np.testing.assert_array_equal(a_g, a_u)


def test_anti_deterministic_grouped_equals_ungrouped():
    nodes = mk_nodes(24)
    pods = mk_pods(20, "anti")
    a_g, _ = solve(nodes, pods, "first", GROUP)
    a_u, _ = solve(nodes, pods, "first", 0)
    np.testing.assert_array_equal(a_g, a_u)


def _oracle_validate(nodes, pods, assignments, nbatch):
    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    errors = oracle.validate_assignments(pods, list(assignments), names=names)
    assert not errors, "\n".join(errors[:5])


from _hypothesis_compat import given, settings, st


@settings(max_examples=8, deadline=None)
@given(seed=st.integers(0, 2**31 - 1))
def test_spread_random_grouped_sequentially_valid(seed):
    """Random-mode quota multi-placement: every placement must be inside
    the oracle tie set given identical history, and the hard skew bound
    must hold at the end. Hypothesis varies the tie-break seed so the
    water-fill / winner / fallback branches all get exercised."""
    nodes = mk_nodes(24)
    pods = mk_pods(48, "spread")
    a, nb = solve(nodes, pods, "random", GROUP, seed=seed)
    assert int((np.asarray(a) >= 0).sum()) == 48
    _oracle_validate(nodes, pods, a, nb)
    zones = np.asarray([int(nb.names[x].split("-")[1]) % 3 for x in a])
    counts = np.bincount(zones, minlength=3)
    assert counts.max() - counts.min() <= 1


@settings(max_examples=8, deadline=None)
@given(seed=st.integers(0, 2**31 - 1))
def test_anti_random_grouped_sequentially_valid(seed):
    nodes = mk_nodes(32)
    pods = mk_pods(24, "anti")
    a, nb = solve(nodes, pods, "random", GROUP, seed=seed)
    assert int((np.asarray(a) >= 0).sum()) == 24
    _oracle_validate(nodes, pods, a, nb)
    # hostname exclusivity
    assert len(set(int(x) for x in a)) == 24


def test_quota_paths_valid_at_device_scale():
    """Padding/bucketing edges at a realistic node count: 512 nodes x
    mixed spread+anti chunks through the grouped solver, oracle-replayed
    with sampled tie-set checks (every 8th step + every failure)."""
    nodes = mk_nodes(512)
    pods = mk_pods(4 * GROUP, "spread") + mk_pods(4 * GROUP, "anti")
    a, nb = solve(nodes, pods, "random", GROUP)
    a = np.asarray(a)
    assert int((a >= 0).sum()) == len(pods)

    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [nb.names[x] if x >= 0 else None for x in a]
    sample = {i for i in range(len(pods)) if i % 8 == 0 or a[i] < 0}
    errors = oracle.validate_assignments(
        pods, list(a), names=names, sample=sample
    )
    assert not errors, "\n".join(errors[:5])
    # invariants over the full assignment
    zones = np.asarray(
        [int(nb.names[x].split("-")[1]) % 3 for x in a[: 4 * GROUP]]
    )
    counts = np.bincount(zones, minlength=3)
    assert counts.max() - counts.min() <= 1
    anti_nodes = [int(x) for x in a[4 * GROUP :]]
    assert len(set(anti_nodes)) == 4 * GROUP  # hostname exclusivity


def test_anti_overload_marks_surplus_unschedulable():
    """More anti pods than nodes: exactly n_nodes place, the rest fail —
    and the grouped result agrees with the ungrouped scan's count."""
    nodes = mk_nodes(8)
    pods = mk_pods(12, "anti")
    a_g, _ = solve(nodes, pods, "random", GROUP)
    placed = int((np.asarray(a_g) >= 0).sum())
    assert placed == 8
    assert len(set(int(x) for x in a_g if x >= 0)) == 8


def test_spread_skew_blocks_when_unavoidable():
    """2 zones only (one zone's nodes all tainted... simpler: 3 pods onto a
    1-node-per-zone cluster with maxSkew 1 — a 4th pod would need a second
    round-robin pass, still feasible; instead make one zone absent)."""
    nodes = [
        MakeNode()
        .name(f"n-{i:04}")
        .capacity({"cpu": "16", "memory": "64Gi", "pods": "2"})
        .label(ZONE, f"z{i % 2}")  # only 2 zones
        .label(HOST, f"n-{i:04}")
        .obj()
        for i in range(4)
    ]
    # pods allowed 2 per zone (pods cap 2/node, 2 nodes/zone): with
    # maxSkew=1 all 8 can place 4/4; a 9th pod has no capacity anyway.
    pods = mk_pods(8, "spread")
    a, nb = solve(nodes, pods, "random", GROUP)
    assert int((np.asarray(a) >= 0).sum()) == 8
    zones = np.asarray([int(nb.names[x].split("-")[1]) % 2 for x in a])
    counts = np.bincount(zones, minlength=2)
    assert abs(int(counts[0]) - int(counts[1])) <= 1
