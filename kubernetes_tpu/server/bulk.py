"""Bulk tensor gRPC service (SURVEY §6.8): the wide-pipe companion to the
per-pod JSON webhook, for workloads where per-pod JSON would dominate —
the 50k-pod single-shot rebalance.

Service ``kubernetestpu.Bulk``, methods (all unary, payloads framed by
server/tensorcodec.py — columnar arrays + one JSON header):

- ``SyncNodes``: upsert a node set from columnar arrays
  (names in meta; cpu_milli/mem_bytes/max_pods arrays; optional labels in
  meta). The node-delta path: only changed nodes need re-sending.
- ``Solve``: schedule a columnar pod batch (cpu_milli/mem_bytes/priority
  arrays) against the current node state.
  meta.mode = "exact" (sequential-parity scan, grouped fast path when
  eligible) | "single_shot" (auction; the rebalance engine).
  meta.commit = true writes bindings into the cluster state (pods must
  carry names in meta); default is advisory — assignments return but no
  state changes, mirroring the webhook's advisory filter/prioritize.
  Response: assignments int32 [P] (index into meta.nodes of the reply,
  -1 = unschedulable).
- ``Evaluate``: score a columnar pod batch -> scores int32 [P, N]
  (-1 = infeasible), the bulk analog of /filter + /prioritize in one call.

Columnar pods deliberately carry only resources + priority: richer pods
(affinity, spread, ports) flow through the JSON ingest + webhook path where
the full object model applies. This mirrors the north-star workload shape
(BASELINE.json ladder #5: resource rebalance at 50k x 10k).

Uses grpc.method_handlers_generic_handler with identity serializers —
the wire is opaque bytes (tensorcodec framing); no protoc codegen exists
in this image (grpc_tools is absent), and none is needed.
"""

from __future__ import annotations

import threading

import numpy as np

from ..api.objects import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Node,
    Pod,
)
from ..state.cluster import ApiError, ClusterState
from ..tensorize.schema import (
    CPU_IDX,
    MEM_IDX,
    PodBatch,
    ResourceVocab,
    bucket_pow2,
    build_node_batch,
)
from . import tensorcodec

SERVICE = "kubernetestpu.Bulk"


def columnar_pod_batch(
    cpu_milli: np.ndarray,
    mem_bytes: np.ndarray,
    priority: np.ndarray | None,
    vocab: ResourceVocab,
    keys: list[str] | None = None,
) -> PodBatch:
    """Build a PodBatch straight from columnar arrays — no per-pod Python
    objects on the bulk path (SURVEY §8.8: 1-vCPU host discipline).

    NonZeroRequested defaults (100 mCPU / 200 MB, noderesources/
    resource_allocation.go) apply where a request is zero, matching
    Pod.non_zero_request()."""
    p = int(cpu_milli.shape[0])
    pp = bucket_pow2(p)
    k = len(vocab)
    req = np.zeros((pp, k), dtype=np.int64)
    req[:p, CPU_IDX] = cpu_milli
    req[:p, MEM_IDX] = mem_bytes
    nonzero = np.zeros((pp, 2), dtype=np.int64)
    nonzero[:p, 0] = np.where(cpu_milli > 0, cpu_milli, 100)
    nonzero[:p, 1] = np.where(mem_bytes > 0, mem_bytes, 200 * 1024 * 1024)
    prio = np.zeros(pp, dtype=np.int32)
    if priority is not None:
        prio[:p] = priority
    valid = np.zeros(pp, dtype=bool)
    valid[:p] = True
    return PodBatch(
        vocab=vocab,
        keys=keys if keys is not None else [f"default/bulk-{i}" for i in range(p)],
        num_pods=p,
        padded=pp,
        req=req,
        req_mask=req > 0,
        feasible_static=np.ones(pp, dtype=bool),
        nonzero_req=nonzero,
        priority=prio,
        valid=valid,
    )


class BulkCore:
    """Method implementations as bytes -> bytes functions (testable without
    a socket, like ExtenderCore's dict -> dict handlers)."""

    def __init__(
        self, cluster: ClusterState, solver_config=None, exchange=None,
        tracer=None,
    ):
        self.cluster = cluster
        self._lock = threading.Lock()
        from ..solver.evaluate import BatchEvaluator
        from ..solver.exact import ExactSolver
        from ..solver.single_shot import SingleShotSolver

        self.exact = ExactSolver(solver_config)
        self.evaluator = BatchEvaluator(solver_config)
        self.single_shot = SingleShotSolver()
        # fleet occupancy hub (fleet/occupancy.py): lazily created on
        # the first ExchangeOccupancy call unless an in-process fleet
        # shares its hub explicitly
        self.exchange = exchange
        # obs span layer: server-side half of the cross-process trace
        # propagation — a Solve request carrying meta.trace continues
        # the CALLER's trace (id + parent span + replica + incarnation
        # as span attributes) instead of starting an anonymous one.
        # Default: a disabled tracer (one attribute check per call).
        if tracer is None:
            from ..obs import Tracer

            tracer = Tracer(enabled=False)
        self.tracer = tracer

    # -- helpers --

    def _node_view(self):
        nodes = self.cluster.list_nodes()
        pods_by_node: dict[str, list[Pod]] = {}
        for p in self.cluster.list_pods():
            if p.node_name:
                pods_by_node.setdefault(p.node_name, []).append(p)
        return nodes, pods_by_node

    # -- methods --

    def sync_nodes(self, data: bytes) -> bytes:
        meta, arrays = tensorcodec.decode(data)
        names = meta.get("names") or []
        labels = meta.get("labels") or [{}] * len(names)
        cpu = arrays["cpu_milli"]
        mem = arrays["mem_bytes"]
        max_pods = arrays.get("max_pods")
        applied = 0
        with self._lock:
            for i, name in enumerate(names):
                node = Node(
                    name=name,
                    labels=dict(labels[i]) if i < len(labels) else {},
                    allocatable={
                        RESOURCE_CPU: int(cpu[i]),
                        RESOURCE_MEMORY: int(mem[i]),
                        RESOURCE_PODS: (
                            int(max_pods[i]) if max_pods is not None else 110
                        ),
                    },
                )
                try:
                    self.cluster.create_node(node)
                except ApiError:
                    self.cluster.update_node(node)
                applied += 1
        return tensorcodec.encode({"applied": applied})

    def solve(self, data: bytes) -> bytes:
        meta, arrays = tensorcodec.decode(data)
        mode = meta.get("mode") or "exact"
        commit = bool(meta.get("commit"))
        names = meta.get("names")
        # cross-process trace context (obs tentpole): the caller's
        # trace id / parent span / replica / incarnation ride the
        # request meta; the server-side span joins that trace so the
        # bulk solve appears in the SAME trace as the caller's batch
        tctx = meta.get("trace") or {}
        with self.tracer.span(
            "bulk_solve",
            trace_id=tctx.get("trace"),
            mode=mode,
            commit=commit,
            **{
                k: tctx[k]
                for k in ("parent", "replica", "incarnation")
                if tctx.get(k) is not None
            },
        ), self._lock:
            nodes, pods_by_node = self._node_view()
            if not nodes:
                return tensorcodec.encode({"error": "no nodes ingested"})
            batch = build_node_batch(nodes, pods_by_node)
            pbatch = columnar_pod_batch(
                arrays["cpu_milli"],
                arrays["mem_bytes"],
                arrays.get("priority"),
                batch.vocab,
                keys=names,
            )
            if mode == "single_shot":
                assignments = self.single_shot.solve(batch, pbatch)
            else:
                assignments = self.exact.solve(batch, pbatch)
            committed = 0
            commit_errors: dict[str, str] = {}
            if commit and names:
                from ..api.objects import Container

                default_ns = meta.get("namespace") or "default"
                for i, (key, a) in enumerate(zip(names, assignments)):
                    if a < 0:
                        continue
                    # an "ns/name"-shaped key carries its own namespace;
                    # bare names fall back to the request's (a caller
                    # mixing namespaces must not land pods in the wrong
                    # one — ADVICE r3)
                    ns, _, pod_name = key.rpartition("/")
                    ns = ns or default_ns
                    # one create+bind per placed pod; advisory callers skip.
                    # Failures are reported per pod so the reply can never
                    # silently diverge from committed state; a bind failure
                    # rolls the created pod back (no unbound orphans).
                    created = False
                    try:
                        self.cluster.create_pod(
                            Pod(
                                name=pod_name,
                                namespace=ns,
                                containers=(
                                    Container(
                                        name="c",
                                        requests={
                                            RESOURCE_CPU: int(
                                                arrays["cpu_milli"][i]
                                            ),
                                            RESOURCE_MEMORY: int(
                                                arrays["mem_bytes"][i]
                                            ),
                                        },
                                    ),
                                ),
                            )
                        )
                        created = True
                        self.cluster.bind(ns, pod_name, batch.names[int(a)])
                        committed += 1
                    except ApiError as e:
                        commit_errors[key] = e.reason
                        if created:
                            try:
                                self.cluster.delete_pod(ns, pod_name)
                            except ApiError:
                                pass
        reply_meta: dict = {"nodes": batch.names, "mode": mode}
        if commit:
            reply_meta["committed"] = committed
            if commit_errors:
                reply_meta["commitErrors"] = commit_errors
        return tensorcodec.encode(
            reply_meta,
            {"assignments": np.asarray(assignments, dtype=np.int32)},
        )

    def exchange_occupancy(self, data: bytes) -> bytes:
        """Fleet cross-shard occupancy exchange (fleet/occupancy.py):
        the sender's node inventory + pod rows replace its previous
        view on the hub; the reply carries the merged rows of every
        OTHER replica, framed the same way. One unary call per
        reconcile refresh — compact by construction (label-bearing
        placements only)."""
        from ..fleet.occupancy import ingest_payload

        return ingest_payload(self._hub(), data)

    def _hub(self):
        from ..fleet.occupancy import OccupancyExchange

        with self._lock:
            if self.exchange is None:
                self.exchange = OccupancyExchange()
            return self.exchange

    def hub_op(self, data: bytes, ctx=None) -> bytes:
        """Occupancy-hub operation dispatch: the full OccupancyExchange
        surface (stage / fenced compare-and-stage / commit / withdraw /
        idempotent apply_ops flush / retire / handoff / degraded flags
        / views / replication catch-up / status) as one unary RPC, so N
        cross-process replicas share ONE hub with the in-process
        semantics intact. The op table itself lives in
        ``fleet.occupancy.dispatch_hub_op`` — shared verbatim with the
        in-process LocalHubClient, so the two transports cannot drift —
        and every reply carries the hub's ``epoch`` for the client-side
        monotone fencing check. Error mapping — the wire half of the
        typed-conflict contract:

        - ``ExchangeUnreachable`` (the sim's partition seam / a downed
          hub) -> UNAVAILABLE: a transport-class failure the client
          surfaces as ExchangeUnreachable again;
        - ``HubDeposed`` (this hub does not hold the primary lease —
          a deposed old primary or an unpromoted standby) ->
          PERMISSION_DENIED: RemoteOccupancyExchange rotates to the
          next endpoint, never retries here;
        - ``AdmitConflict`` (CAS lost its version race) -> ABORTED;
          ``AdmitConflict(fenced=True)`` (hub write fence) ->
          FAILED_PRECONDITION. Both are SEMANTIC rejections: BulkClient
          never retries them (retrying a lost race would re-land the
          write the CAS exists to reject)."""
        import grpc

        from ..fleet.occupancy import (
            AdmitConflict,
            ExchangeUnreachable,
            HubDeposed,
            dispatch_hub_op,
        )

        meta, _arrays = tensorcodec.decode(data)
        op = meta.get("op") or ""
        hub = self._hub()
        # hub spans carry the epoch: one span per HubOp with the hub's
        # identity attributes, so a trace crossing a failover shows
        # WHICH hub incarnation served each op (disabled tracer = one
        # attribute check)
        with self.tracer.span(
            "hub_op", op=op, hub_epoch=hub.hub_epoch,
        ):
            try:
                out = dispatch_hub_op(hub, op, meta)
            except HubDeposed as e:
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
                raise
            except ExchangeUnreachable as e:
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                raise
            except AdmitConflict as e:
                if ctx is not None:
                    ctx.abort(
                        grpc.StatusCode.FAILED_PRECONDITION
                        if e.fenced
                        else grpc.StatusCode.ABORTED,
                        str(e),
                    )
                raise
            except ValueError as e:
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                raise
        return tensorcodec.encode(out)

    def evaluate(self, data: bytes) -> bytes:
        meta, arrays = tensorcodec.decode(data)
        from ..tensorize.interpod import trivial_interpod_tensors
        from ..tensorize.plugins import (
            trivial_port_tensors,
            trivial_static_tensors,
        )
        from ..tensorize.spread import trivial_spread_tensors

        with self._lock:
            nodes, pods_by_node = self._node_view()
            if not nodes:
                return tensorcodec.encode({"error": "no nodes ingested"})
            batch = build_node_batch(nodes, pods_by_node)
            pbatch = columnar_pod_batch(
                arrays["cpu_milli"],
                arrays["mem_bytes"],
                arrays.get("priority"),
                batch.vocab,
            )
            static = trivial_static_tensors(
                pbatch, batch.padded, batch.schedulable
            )
            ports = trivial_port_tensors(pbatch, batch.padded)
            spread = trivial_spread_tensors(pbatch, batch.padded, static.c_pad)
            interpod = trivial_interpod_tensors(
                pbatch, batch.padded, static.c_pad
            )
            out = self.evaluator.evaluate_tensors(
                batch, pbatch, static, ports, spread, interpod
            )[:, : batch.num_nodes]
        return tensorcodec.encode(
            {"nodes": batch.names},
            {"scores": np.ascontiguousarray(out, dtype=np.int32)},
        )


def make_grpc_server(core: BulkCore, port: int = 0, host: str = "127.0.0.1"):
    """Returns (server, bound_port). Identity serializers: the tensorcodec
    framing IS the message format."""
    import grpc
    from concurrent import futures

    ident = lambda b: b  # noqa: E731

    def unary(fn):
        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(req),
            request_deserializer=ident,
            response_serializer=ident,
        )

    def unary_ctx(fn):
        # the handler needs the ServicerContext to abort with typed
        # status codes (the HubOp conflict mapping)
        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(req, ctx),
            request_deserializer=ident,
            response_serializer=ident,
        )

    handler = grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "SyncNodes": unary(core.sync_nodes),
            "Solve": unary(core.solve),
            "Evaluate": unary(core.evaluate),
            "ExchangeOccupancy": unary(core.exchange_occupancy),
            "HubOp": unary_ctx(core.hub_op),
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    bound = server.add_insecure_port(f"{host}:{port}")
    return server, bound


def serve_bulk(
    cluster: ClusterState,
    port: int,
    host: str = "127.0.0.1",
    solver_config=None,
    tracer=None,
):
    """Start the bulk gRPC server (non-blocking); returns the grpc server."""
    core = BulkCore(cluster, solver_config=solver_config, tracer=tracer)
    server, bound = make_grpc_server(core, port=port, host=host)
    server.start()
    return server


# transient gRPC status codes worth retrying: the server is alive but
# this call lost (connection churn, queue overflow, deadline) — the
# request is idempotent on the bulk surface (SyncNodes upserts, Solve
# without commit is advisory, ExchangeOccupancy replaces wholesale)
_RETRYABLE_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED")


class BulkClient:
    """Columnar in, columnar out — now with production-grade call
    hygiene: every RPC carries a deadline, and transient failures
    (UNAVAILABLE / DEADLINE_EXCEEDED / RESOURCE_EXHAUSTED, plus broken
    connections) retry with FULL-JITTER bounded exponential backoff
    (each wait drawn uniformly from [0, base * 2^attempt) — N clients
    whose server just failed over must not re-arrive in lockstep and
    thundering-herd the standby), counted by
    ``scheduler_bulk_retry_total``. A call that keeps failing raises
    the last error — the caller sees exactly one exception after the
    budget, not a raw flake on the first blip.

    ``Solve`` with ``commit=True`` is NOT blindly idempotent (a lost
    reply can leave bindings committed), so commit calls do not
    retry; the per-pod ``commitErrors`` map is the recovery surface.
    """

    def __init__(
        self,
        target: str,
        *,
        retries: int = 3,
        deadline_s: float = 30.0,
        backoff_base_s: float = 0.05,
        clock=None,
        backoff_rng=None,
    ):
        import grpc
        import random

        from ..utils.clock import Clock

        self._grpc = grpc
        self.retries = max(int(retries), 0)
        self.deadline_s = float(deadline_s)
        self.backoff_base_s = float(backoff_base_s)
        self._clock = clock or Clock()
        # jitter stream: seeded by the target string so seeded runs
        # (the sim's --selfcheck) stay deterministic; tests inject
        # their own to pin exact draws
        self._backoff_rng = (
            backoff_rng
            if backoff_rng is not None
            else random.Random(f"bulk-backoff/{target}")
        )
        ident = lambda b: b  # noqa: E731
        self._channel = grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=ident,
            response_deserializer=ident,
        )
        self._sync = self._channel.unary_unary(
            f"/{SERVICE}/SyncNodes",
            request_serializer=ident,
            response_deserializer=ident,
        )
        self._eval = self._channel.unary_unary(
            f"/{SERVICE}/Evaluate",
            request_serializer=ident,
            response_deserializer=ident,
        )
        self._exchange = self._channel.unary_unary(
            f"/{SERVICE}/ExchangeOccupancy",
            request_serializer=ident,
            response_deserializer=ident,
        )
        self._hub_op = self._channel.unary_unary(
            f"/{SERVICE}/HubOp",
            request_serializer=ident,
            response_deserializer=ident,
        )

    def _retryable(self, err: Exception) -> bool:
        if isinstance(err, ConnectionError):
            return True
        if isinstance(err, self._grpc.RpcError):
            code = getattr(err, "code", lambda: None)()
            return code is not None and code.name in _RETRYABLE_CODES
        return False

    def _call(self, method: str, fn, payload: bytes, retry: bool = True):
        """One deadline-bounded RPC with full-jitter bounded-backoff
        retries on transient errors (AWS-style full jitter: the wait is
        uniform over [0, cap), where cap doubles per attempt — plain
        exponential backoff keeps simultaneous losers synchronized,
        which is exactly wrong during a fleet-wide hub failover)."""
        attempts = self.retries + 1 if retry else 1
        last = None
        for attempt in range(attempts):
            if attempt:
                from .. import metrics

                metrics.bulk_retry_total.labels(method).inc()
                self._clock.sleep(
                    self._backoff_rng.uniform(
                        0.0, self.backoff_base_s * (2 ** (attempt - 1))
                    )
                )
            try:
                return fn(payload, timeout=self.deadline_s)
            except Exception as e:
                if not self._retryable(e):
                    raise
                last = e
        raise last

    def sync_nodes(self, names, cpu_milli, mem_bytes, max_pods=None, labels=None):
        arrays = {
            "cpu_milli": np.asarray(cpu_milli, dtype=np.int64),
            "mem_bytes": np.asarray(mem_bytes, dtype=np.int64),
        }
        if max_pods is not None:
            arrays["max_pods"] = np.asarray(max_pods, dtype=np.int32)
        meta = {"names": list(names)}
        if labels is not None:
            meta["labels"] = list(labels)
        reply = self._call(
            "SyncNodes", self._sync, tensorcodec.encode(meta, arrays)
        )
        return tensorcodec.decode(reply)[0]

    def solve(self, cpu_milli, mem_bytes, priority=None, mode="exact",
              names=None, commit=False, namespace=None, trace=None):
        arrays = {
            "cpu_milli": np.asarray(cpu_milli, dtype=np.int64),
            "mem_bytes": np.asarray(mem_bytes, dtype=np.int64),
        }
        if priority is not None:
            arrays["priority"] = np.asarray(priority, dtype=np.int32)
        meta = {"mode": mode, "commit": commit}
        if trace is not None:
            # cross-process trace propagation: a dict like
            # {"trace": <id>, "parent": <span id>, "replica": ...,
            # "incarnation": ...} — the server-side bulk_solve span
            # joins the caller's trace instead of starting its own
            meta["trace"] = dict(trace)
        if names is not None:
            meta["names"] = list(names)
        if namespace is not None:
            # commit fallback namespace for bare (un-prefixed) names;
            # "ns/name"-shaped names carry their own
            meta["namespace"] = namespace
        reply = self._call(
            "Solve", self._solve, tensorcodec.encode(meta, arrays),
            # a committing solve mutates cluster state: a lost REPLY
            # would make the retry double-create — surface the error
            retry=not commit,
        )
        return tensorcodec.decode(reply)

    def evaluate(self, cpu_milli, mem_bytes, priority=None):
        arrays = {
            "cpu_milli": np.asarray(cpu_milli, dtype=np.int64),
            "mem_bytes": np.asarray(mem_bytes, dtype=np.int64),
        }
        if priority is not None:
            arrays["priority"] = np.asarray(priority, dtype=np.int32)
        reply = self._call(
            "Evaluate", self._eval, tensorcodec.encode({}, arrays)
        )
        return tensorcodec.decode(reply)

    def hub_op(self, op: str, **meta) -> dict:
        """One occupancy-hub operation (the HubOp method): meta in,
        reply meta out. Transient transport failures retry like every
        other bulk RPC; ABORTED / FAILED_PRECONDITION — the hub's typed
        CAS-conflict and fence rejections — are SEMANTIC and surface
        immediately (never retried: a blind retry of a lost admit race
        would re-land the write the compare-and-stage rejected,
        mirroring the committing-Solve never-retries rule)."""
        meta["op"] = op
        reply = self._call(
            "HubOp", self._hub_op, tensorcodec.encode(meta)
        )
        return tensorcodec.decode(reply)[0]

    def exchange_occupancy(self, replica, version, node_rows, pod_rows):
        """Fleet occupancy exchange round trip: publish this replica's
        rows, return (version, peer node rows, peer pod rows)."""
        from ..fleet.occupancy import decode_rows, encode_rows

        reply = self._call(
            "ExchangeOccupancy", self._exchange,
            encode_rows(replica, version, node_rows, pod_rows),
        )
        _replica, v, nodes, pods = decode_rows(reply)
        return v, nodes, pods

    def close(self):
        self._channel.close()
