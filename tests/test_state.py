"""State plane: cluster service (rv/conflicts/watch/binding), scheduler cache
(assume/forget/expire/generations), snapshot incrementality, queue ordering
and backoff — semantics from cache.go / scheduling_queue.go, with fake
clocks as in the reference's queue tests."""

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.state.cache import CacheError, SchedulerCache
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.state.snapshot import Snapshot
from kubernetes_tpu.utils.clock import FakeClock


def node(name, cpu="4", mem="8Gi", pods="10"):
    return MakeNode().name(name).capacity({"cpu": cpu, "memory": mem, "pods": pods}).obj()


def pod(name, cpu="100m", prio=None, ns="default"):
    mp = MakePod().name(name).namespace(ns).req({"cpu": cpu})
    if prio is not None:
        mp = mp.priority(prio)
    return mp.obj()


class TestClusterState:
    def test_crud_and_rv_monotonic(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        p = cs.create_pod(pod("p1"))
        rv1 = p.resource_version
        cs.bind("default", "p1", "n1")
        assert cs.get_pod("default", "p1").node_name == "n1"
        assert cs.get_pod("default", "p1").resource_version > rv1

    def test_bind_rejects_double_and_missing_node(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))
        cs.bind("default", "p1", "n1")
        with pytest.raises(ApiError) as e:
            cs.bind("default", "p1", "n1")
        assert e.value.reason == "Conflict"
        cs.create_pod(pod("p2"))
        with pytest.raises(ApiError) as e:
            cs.bind("default", "p2", "ghost")
        assert e.value.reason == "NotFound"

    def test_optimistic_concurrency(self):
        cs = ClusterState()
        n = cs.create_node(node("n1"))
        stale = n.resource_version
        cs.update_node(n)  # bumps rv
        with pytest.raises(ApiError) as e:
            cs.update_node(n, expect_rv=stale)
        assert e.value.reason == "Conflict"

    def test_watch_order(self):
        cs = ClusterState()
        seen = []
        cs.subscribe(lambda ev: seen.append((ev.type, ev.kind)))
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))
        cs.bind("default", "p1", "n1")
        cs.delete_pod("default", "p1")
        assert seen == [
            ("ADDED", "Node"),
            ("ADDED", "Pod"),
            ("MODIFIED", "Pod"),
            ("DELETED", "Pod"),
        ]

    def test_bind_fault_injection(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))

        def boom(pod_, node_name):
            raise ApiError("Conflict", "injected")

        cs.bind_fault = boom
        with pytest.raises(ApiError):
            cs.bind("default", "p1", "n1")
        assert cs.get_pod("default", "p1").node_name == ""


class TestSchedulerCache:
    def test_assume_confirm_flow(self):
        clock = FakeClock()
        c = SchedulerCache(clock)
        c.add_node(node("n1"))
        p = pod("p1")
        c.assume_pod(p, "n1")
        assert c.is_assumed("default/p1")
        assert c.nodes["n1"].used["cpu"] == 100
        c.finish_binding("default/p1")
        bound = pod("p1")
        bound.node_name = "n1"
        c.add_pod(bound)  # watch confirmation
        assert not c.is_assumed("default/p1")
        assert c.nodes["n1"].used["cpu"] == 100  # not double-counted

    def test_forget_releases(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        c.forget_pod("default/p1")
        assert c.nodes["n1"].used.get("cpu", 0) == 0
        assert c.nodes["n1"].pod_count if hasattr(c.nodes["n1"], "pod_count") else True

    def test_assume_expiry(self):
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        c.finish_binding("default/p1")
        clock.advance(31)
        expired = c.cleanup_expired()
        assert expired == ["default/p1"]
        assert c.nodes["n1"].used.get("cpu", 0) == 0

    def test_unfinished_assume_expires_after_ttl(self):
        # pre-PR-8 discrepancy: an assume whose binding cycle died
        # before finish_binding was NEVER reaped, leaking phantom
        # occupancy forever. It now expires after the assume TTL and
        # releases its occupancy (the restart-recovery pass leans on
        # the same release semantics).
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        clock.advance(29)
        assert c.cleanup_expired() == []  # binding still in flight
        clock.advance(2)
        assert c.cleanup_expired() == ["default/p1"]
        assert c.nodes["n1"].used.get("cpu", 0) == 0  # occupancy released
        assert not c.is_assumed("default/p1")

    def test_protected_unfinished_assume_survives_ttl(self):
        # Permit-parked pods legitimately sit assumed-unfinished across
        # cycles: the WaitingPods map protects them from the unfinished
        # reap (their rollback deadline is the permit timeout)
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        clock.advance(300)
        assert c.cleanup_expired(protected=frozenset({"default/p1"})) == []
        assert c.is_assumed("default/p1")

    def test_double_assume_rejected(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        with pytest.raises(CacheError):
            c.assume_pod(pod("p1"), "n1")

    def test_node_removed_with_pods_keeps_ghost(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        bound = pod("p1")
        bound.node_name = "n1"
        c.add_pod(bound)
        c.remove_node("n1")
        assert c.nodes["n1"].node is None  # ghost holding the pod
        c.remove_pod("default/p1")
        assert "n1" not in c.nodes


class TestSnapshot:
    def test_incremental_update(self):
        c = SchedulerCache(FakeClock())
        for i in range(3):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        b = snap.update(c)
        assert b.num_nodes == 3
        assert b.valid.sum() == 3
        # place a pod; only that column should change
        bound = pod("p1", cpu="500m")
        bound.node_name = "n1"
        c.add_pod(bound)
        i1 = snap.slot_of("n1")
        before = b.used.copy()
        b2 = snap.update(c)
        assert b2.used[0, i1] == 500
        unchanged = [snap.slot_of("n0"), snap.slot_of("n2")]
        for j in unchanged:
            assert (b2.used[:, j] == before[:, j]).all()

    def test_node_remove_and_slot_reuse(self):
        c = SchedulerCache(FakeClock())
        for i in range(3):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        snap.update(c)
        slot = snap.slot_of("n1")
        c.remove_node("n1")
        b = snap.update(c)
        assert not b.valid[slot]
        c.add_node(node("n9"))
        b = snap.update(c)
        assert snap.slot_of("n9") == slot  # reused
        assert b.valid[slot]

    def test_high_freed_slot_with_multiple_adds_no_collision(self):
        """Regression (sim-caught overcommit): removing a HIGH slot and
        adding more nodes than _free holds in ONE update used to
        double-assign the freed slot — max+1 fresh-slot counting walked
        back up into a slot _free had already handed out, two nodes
        shared a column, and the second write erased the first node's
        usage (the solver then overcommitted against understated
        tables)."""
        c = SchedulerCache(FakeClock())
        for i in range(9):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        snap.update(c)
        # free a LOW slot, then a HIGH slot, then add three nodes in one
        # update: free=[low, high] pops high first, and the fresh-slot
        # path must not re-issue it
        c.remove_node("n7")
        snap.update(c)
        c.remove_node("n8")
        for i in range(9, 12):
            c.add_node(node(f"n{i}"))
        b = snap.update(c)
        slots = [snap.slot_of(f"n{i}") for i in (0, 1, 2, 3, 4, 5, 6, 9, 10, 11)]
        assert len(set(slots)) == len(slots), slots
        # every column carries ITS node's tables (no silent overwrite)
        for i in (9, 10, 11):
            s = snap.slot_of(f"n{i}")
            assert b.valid[s]
            assert b.allocatable[0, s] == 4000
            assert b.used[0, s] == 0

    def test_capacity_growth_preserves_slots(self):
        c = SchedulerCache(FakeClock())
        for i in range(100):
            c.add_node(node(f"n{i:03}"))
        snap = Snapshot()
        b = snap.update(c)
        assert b.padded == 128
        s50 = snap.slot_of("n050")
        for i in range(100, 200):
            c.add_node(node(f"n{i:03}"))
        b = snap.update(c)
        assert b.padded == 256
        assert snap.slot_of("n050") == s50
        assert b.allocatable[0, s50] == 4000


class TestPriorityQueue:
    def test_priority_then_fifo_order(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("low1", prio=1))
        clock.advance(1)
        q.add(pod("high", prio=10))
        clock.advance(1)
        q.add(pod("low2", prio=1))
        got = [i.pod.name for i in q.pop_batch(10)]
        assert got == ["high", "low1", "low2"]

    def test_unschedulable_parks_until_move(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        cycle = q.scheduling_cycle
        q.add_unschedulable(info, cycle)
        assert q.pop_batch(1) == []
        clock.advance(60)  # well past any backoff
        q.move_all_to_active_or_backoff("NodeAdd")
        got = q.pop_batch(1)
        assert [i.pod.name for i in got] == ["p1"]

    def test_backoff_grows_and_caps(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        # attempt 1 -> backoff 1s
        (info,) = q.pop_batch(1)
        q.add_unschedulable(info, q.scheduling_cycle)
        q.move_all_to_active_or_backoff()
        assert q.pop_batch(1) == []  # still backing off
        clock.advance(1.01)
        (info,) = q.pop_batch(1)
        # attempt 2 -> 2s
        q.add_unschedulable(info, q.scheduling_cycle)
        q.move_all_to_active_or_backoff()
        clock.advance(1.01)
        assert q.pop_batch(1) == []
        clock.advance(1.0)
        (info,) = q.pop_batch(1)
        assert info.attempts == 3

    def test_move_request_cycle_prevents_lost_wakeup(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        cycle = q.scheduling_cycle
        # event fires while the pod is mid-cycle
        q.move_all_to_active_or_backoff("NodeAdd")
        q.add_unschedulable(info, cycle)
        # pod must NOT be parked: it goes to backoff and becomes ready
        clock.advance(1.01)
        assert [i.pod.name for i in q.pop_batch(1)] == ["p1"]

    def test_five_minute_flush(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        q.add_unschedulable(info, q.scheduling_cycle)
        clock.advance(301)
        q.flush_unschedulable_leftover()
        assert [i.pod.name for i in q.pop_batch(1)] == ["p1"]

    def test_scheduling_gates(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        gated = MakePod().name("g").scheduling_gates(["wait"]).obj()
        q.add(gated)
        assert q.pop_batch(1) == []
        ungated = MakePod().name("g").obj()
        q.update(ungated)
        assert [i.pod.name for i in q.pop_batch(1)] == ["g"]

    def test_delete_pending(self):
        q = PriorityQueue(FakeClock())
        q.add(pod("p1"))
        q.delete("default/p1")
        assert q.pop_batch(1) == []


def test_event_store_ttl_prunes_old_records():
    """Events expire after event_ttl (the reference apiserver's 1h TTL)
    instead of accumulating forever — and a count-bumped OLD record with
    a fresh last_timestamp must not block the sweep (review-caught: the
    sweep scans the whole store, not just the insertion-order head)."""
    from kubernetes_tpu.api.wrappers import MakeNode

    cs = ClusterState()
    n = cs.create_node(MakeNode().name("n1").capacity({"cpu": "1"}).obj())
    cs.event_ttl = 100.0
    cs._events_sweep_at = 3  # sweep once the store holds 3 records
    cs.record_event(n, "HotHead", "recurring", timestamp=0.0)
    cs.record_event(n, "Old", "stale note", timestamp=10.0)
    # the head record keeps recurring within its TTL: fresh
    # last_timestamp, oldest insertion slot
    cs.record_event(n, "HotHead", "recurring", timestamp=95.0)
    cs.record_event(n, "Newer", "fresh note", timestamp=195.0)
    cs.record_event(n, "Latest", "now", timestamp=200.0)
    reasons = {e.reason for e in cs.list_events()}
    assert "Old" not in reasons, "expired record behind a hot head"
    assert {"HotHead", "Newer", "Latest"} <= reasons
    assert cs.list_events(regarding_name="n1")[0].count >= 2


def test_event_store_ttl_small_store_still_prunes():
    """A store below the size-sweep threshold still expires records once
    a full TTL elapses since the last sweep (review-caught: the size-only
    trigger never fired for small stores)."""
    from kubernetes_tpu.api.wrappers import MakeNode

    cs = ClusterState()
    n = cs.create_node(MakeNode().name("n1").capacity({"cpu": "1"}).obj())
    cs.event_ttl = 100.0  # default sweep threshold (256) untouched
    cs.record_event(n, "Old", "stale", timestamp=0.0)
    cs.record_event(n, "Fresh", "new", timestamp=150.0)
    reasons = {e.reason for e in cs.list_events()}
    assert "Old" not in reasons and "Fresh" in reasons


def test_fit_hint_ignores_capacity_shrink_that_still_fits():
    """VERDICT r3 weak #8: a resource-only NodeUpdate that SHRINKS
    allocatable must not wake parked pods that already fit the old
    capacity — the change cannot have unblocked them."""
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig

    clock = FakeClock()
    cs = ClusterState()
    n1 = node("n1", cpu="8")
    cs.create_node(n1)
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    # park two pods as unschedulable: one that always fit n1's resources
    # (rejected elsewhere) and one genuinely resource-blocked
    cs.create_pod(pod("small", cpu="100m"))
    cs.create_pod(pod("big", cpu="6000m"))
    infos = sched.queue.pop_batch(2)
    for info in infos:
        sched.queue.add_unschedulable(info, sched.queue.scheduling_cycle)
    assert sched.queue.pending_counts()["unschedulable"] == 2
    # shrink allocatable 8 -> 4 cpu: small still fits old AND new (the
    # change cannot have unblocked it), big fits neither -> no wakeups
    shrunk = node("n1", cpu="4")
    shrunk.resource_version = cs.get_node("n1").resource_version
    cs.update_node(shrunk)
    assert sched.queue.pending_counts()["unschedulable"] == 2, (
        "a shrink that changes no verdict must wake nothing"
    )
    # grow 4 -> 16 cpu: big fits new but NOT old -> exactly it wakes
    grown = node("n1", cpu="16")
    grown.resource_version = cs.get_node("n1").resource_version
    cs.update_node(grown)
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 1  # small stays parked
    clock.advance(1.1)  # let the moved pod clear its backoff window
    woken = [i.pod.name for i in sched.queue.pop_batch(10)]
    assert woken == ["big"]
