"""InterPodAffinity: oracle unit tests + solver-vs-oracle parity."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import interpod as oip
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.spread import build_spread_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)


def zone_nodes(n, zones=2):
    return [
        MakeNode()
        .name(f"node-{i:03}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", f"z{i % zones}")
        .label("kubernetes.io/hostname", f"node-{i:03}")
        .obj()
        for i in range(n)
    ]


# -- oracle unit tests ------------------------------------------------------


def test_oracle_required_affinity_needs_match_in_domain():
    nodes = zone_nodes(4, 2)
    backend = MakePod().name("be").label("app", "backend").obj()
    all_nodes = [(nodes[0], [backend]), (nodes[1], []), (nodes[2], []), (nodes[3], [])]
    pod = (
        MakePod().name("fe").label("app", "frontend")
        .pod_affinity("zone", match_labels={"app": "backend"})
        .obj()
    )
    # backend in z0 (nodes 0, 2) -> only z0 nodes pass
    assert oip.interpod_filter(pod, nodes[0], all_nodes)
    assert oip.interpod_filter(pod, nodes[2], all_nodes)
    assert not oip.interpod_filter(pod, nodes[1], all_nodes)
    assert not oip.interpod_filter(pod, nodes[3], all_nodes)


def test_oracle_first_pod_exception():
    nodes = zone_nodes(2, 2)
    all_nodes = [(n, []) for n in nodes]
    # self-affine group bootstrap: no match anywhere + self-match -> allowed
    pod = (
        MakePod().name("p0").label("app", "grp")
        .pod_affinity("zone", match_labels={"app": "grp"})
        .obj()
    )
    assert oip.interpod_filter(pod, nodes[0], all_nodes)
    # pod NOT matching its own selector: blocked everywhere
    pod2 = (
        MakePod().name("p1").label("app", "other")
        .pod_affinity("zone", match_labels={"app": "grp"})
        .obj()
    )
    assert not oip.interpod_filter(pod2, nodes[0], all_nodes)


def test_oracle_anti_affinity_blocks_domain():
    nodes = zone_nodes(4, 2)
    noisy = MakePod().name("noisy").label("team", "red").obj()
    all_nodes = [(nodes[0], [noisy]), (nodes[1], []), (nodes[2], []), (nodes[3], [])]
    pod = (
        MakePod().name("p").label("x", "y")
        .pod_anti_affinity("zone", match_labels={"team": "red"})
        .obj()
    )
    assert not oip.interpod_filter(pod, nodes[0], all_nodes)
    assert not oip.interpod_filter(pod, nodes[2], all_nodes)  # same zone z0
    assert oip.interpod_filter(pod, nodes[1], all_nodes)


def test_oracle_existing_anti_symmetry():
    nodes = zone_nodes(4, 2)
    # existing pod REPELS app=web from its zone
    grump = (
        MakePod().name("grump").label("team", "solo")
        .pod_anti_affinity("zone", match_labels={"app": "web"})
        .obj()
    )
    all_nodes = [(nodes[1], [grump]), (nodes[0], []), (nodes[2], []), (nodes[3], [])]
    web = MakePod().name("w").label("app", "web").obj()
    assert oip.interpod_filter(web, nodes[0], all_nodes)  # z0 fine
    assert not oip.interpod_filter(web, nodes[1], all_nodes)  # grump's zone z1
    assert not oip.interpod_filter(web, nodes[3], all_nodes)  # z1 too
    # non-matching pod unaffected
    other = MakePod().name("o").label("app", "db").obj()
    assert oip.interpod_filter(other, nodes[1], all_nodes)


def test_oracle_preferred_scores():
    nodes = zone_nodes(4, 2)
    be = MakePod().name("be").label("app", "backend").obj()
    all_nodes = [(nodes[0], [be]), (nodes[1], []), (nodes[2], []), (nodes[3], [])]
    pod = (
        MakePod().name("fe")
        .preferred_pod_affinity(10, "zone", match_labels={"app": "backend"})
        .obj()
    )
    raw = oip.interpod_raw_scores(pod, nodes, all_nodes)
    assert raw == [10, 0, 10, 0]
    norm = oip.normalize_scores(raw)
    assert norm == [100, 0, 100, 0]


# -- solver parity ----------------------------------------------------------


def run_solver(nodes, pods, placed_by_node=None, tie_break="first"):
    placed_by_node = placed_by_node or {}
    all_pods = pods + [p for ps in placed_by_node.values() for p in ps]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed_by_node, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    placed_by_slot = {
        i: placed_by_node[n.name]
        for i, n in enumerate(nodes)
        if n.name in placed_by_node
    }
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, placed_by_slot, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, placed_by_slot,
        nbatch.padded, static.c_pad,
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, placed_by_slot,
        nbatch.padded, static.c_pad,
    )
    solver = ExactSolver(ExactSolverConfig(tie_break=tie_break))
    return solver.solve(nbatch, pbatch, static, ports, spread, interpod), nbatch


def assert_parity(nodes, pods, placed_by_node=None):
    assignments, nbatch = run_solver(nodes, pods, placed_by_node)
    oracle = FullOracle(make_oracle_nodes(nodes, placed_by_node))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    errors = oracle.validate_assignments(pods, list(assignments), names=names)
    assert not errors, "\n".join(errors[:5])
    return assignments


def test_affinity_follows_backend():
    nodes = zone_nodes(4, 2)
    be = MakePod().name("be").label("app", "backend").node("node-000").obj()
    pods = [
        MakePod().name(f"fe{i}").label("app", "frontend")
        .req({"cpu": "100m"})
        .pod_affinity("zone", match_labels={"app": "backend"})
        .obj()
        for i in range(3)
    ]
    a = assert_parity(nodes, pods, {"node-000": [be]})
    assert all(x >= 0 and x % 2 == 0 for x in a)  # z0 only


def test_anti_affinity_one_per_node():
    nodes = zone_nodes(4, 2)
    pods = [
        MakePod().name(f"s{i}").label("app", "solo")
        .req({"cpu": "100m"})
        .pod_anti_affinity("kubernetes.io/hostname", match_labels={"app": "solo"})
        .obj()
        for i in range(6)
    ]
    a = assert_parity(nodes, pods)
    placed = [x for x in a if x >= 0]
    assert len(placed) == 4  # one per node
    assert len(set(placed)) == 4
    assert list(a).count(-1) == 2


def test_self_affine_group_bootstraps_and_clusters():
    nodes = zone_nodes(6, 3)
    pods = [
        MakePod().name(f"g{i}").label("app", "grp")
        .req({"cpu": "100m"})
        .pod_affinity("zone", match_labels={"app": "grp"})
        .obj()
        for i in range(4)
    ]
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)
    zones = {int(x) % 3 for x in a}
    assert len(zones) == 1  # the group stays in one zone


def test_existing_anti_symmetry_through_solver():
    nodes = zone_nodes(4, 2)
    grump = (
        MakePod().name("grump").label("team", "solo").node("node-001")
        .pod_anti_affinity("zone", match_labels={"app": "web"})
        .obj()
    )
    pods = [
        MakePod().name(f"w{i}").label("app", "web").req({"cpu": "100m"}).obj()
        for i in range(3)
    ]
    a = assert_parity(nodes, pods, {"node-001": [grump]})
    assert all(x >= 0 and x % 2 == 0 for x in a)  # pushed to z0


def test_batch_pods_repel_each_other():
    # anti-affinity among batch pods placed in the SAME scan: the in-batch
    # symmetry update (ex_owned fold-in) must block later pods
    nodes = zone_nodes(3, 3)
    pods = [
        MakePod().name(f"z{i}").label("app", "zoned")
        .req({"cpu": "100m"})
        .pod_anti_affinity("zone", match_labels={"app": "zoned"})
        .obj()
        for i in range(5)
    ]
    a = assert_parity(nodes, pods)
    placed = [x for x in a if x >= 0]
    assert len(placed) == 3  # one per zone
    assert len(set(x % 3 for x in placed)) == 3
    assert list(a).count(-1) == 2


def test_preferred_affinity_steers():
    nodes = zone_nodes(4, 2)
    be = MakePod().name("be").label("app", "backend").node("node-001").obj()
    pods = [
        MakePod().name(f"p{i}")
        .req({"cpu": "100m"})
        .preferred_pod_affinity(50, "zone", match_labels={"app": "backend"})
        .obj()
        for i in range(3)
    ]
    a = assert_parity(nodes, pods, {"node-001": [be]})
    assert all(x % 2 == 1 for x in a)  # z1 preferred


def test_hard_pod_affinity_weight_symmetry_scoring():
    # existing pod with REQUIRED affinity toward app=web: symmetric scoring
    # nudges web pods toward its zone via hardPodAffinityWeight
    nodes = zone_nodes(4, 2)
    lover = (
        MakePod().name("lover").label("team", "fans").node("node-001")
        .pod_affinity("zone", match_labels={"app": "web"})
        .obj()
    )
    pods = [
        MakePod().name(f"w{i}").label("app", "web").req({"cpu": "100m"}).obj()
        for i in range(2)
    ]
    # NB: lover itself violates its own required affinity (no web pods yet)
    # but it is already placed — the scheduler only checks incoming pods.
    a = assert_parity(nodes, pods, {"node-001": [lover]})
    assert all(x >= 0 and x % 2 == 1 for x in a)


def test_match_label_keys_interpod():
    # anti-affinity with matchLabelKeys=[version]: only same-version pods
    # repel; different versions co-exist per zone
    from kubernetes_tpu.api.labels import selector_from_match_labels
    from kubernetes_tpu.api.objects import Affinity, PodAffinity, PodAffinityTerm

    nodes = zone_nodes(4, 2)
    pods = []
    for i in range(4):
        b = (
            MakePod().name(f"v{i}").label("app", "web")
            .label("version", f"v{i % 2}").req({"cpu": "100m"})
        )
        b._pod.affinity = Affinity(
            pod_anti_affinity=PodAffinity(
                required=(
                    PodAffinityTerm(
                        label_selector=selector_from_match_labels({"app": "web"}),
                        topology_key="zone",
                        match_label_keys=("version",),
                    ),
                )
            )
        )
        pods.append(b.obj())
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)
    # same-version pods must sit in different zones
    for v in range(2):
        zs = [int(a[i]) % 2 for i in range(4) if i % 2 == v]
        assert len(set(zs)) == 2


def test_hard_pod_affinity_weight_plumbed():
    # non-default hardPodAffinityWeight must flow tensorizer<->oracle alike
    from kubernetes_tpu.ops.oracle.profile import ProfileWeights

    nodes = zone_nodes(4, 2)
    lover = (
        MakePod().name("lover").label("team", "fans").node("node-001")
        .pod_affinity("zone", match_labels={"app": "web"})
        .obj()
    )
    pods = [
        MakePod().name(f"w{i}").label("app", "web").req({"cpu": "100m"}).obj()
        for i in range(2)
    ]
    placed = {"node-001": [lover]}
    all_pods = pods + [lover]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {1: [lover]}, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, {1: [lover]},
        nbatch.padded, static.c_pad,
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, {1: [lover]},
        nbatch.padded, static.c_pad, hard_pod_affinity_weight=7,
    )
    solver = ExactSolver(ExactSolverConfig(tie_break="first"))
    a = solver.solve(nbatch, pbatch, static, ports, spread, interpod)
    oracle = FullOracle(
        make_oracle_nodes(nodes, placed),
        ProfileWeights(hard_pod_affinity=7),
    )
    names = [nbatch.names[x] if x >= 0 else None for x in a]
    errors = oracle.validate_assignments(pods, list(a), names=names)
    assert not errors, errors[:3]
    assert all(x % 2 == 1 for x in a)


def test_mixed_affinity_cluster_parity():
    rng = np.random.default_rng(11)
    nodes = zone_nodes(8, 2)
    placed = {
        "node-000": [MakePod().name("be0").label("app", "backend").node("node-000").obj()],
        "node-003": [MakePod().name("be1").label("app", "backend").node("node-003").obj()],
    }
    pods = []
    for i in range(20):
        b = MakePod().name(f"m{i:02}").req({"cpu": "200m"})
        r = rng.random()
        if r < 0.3:
            b = b.label("app", "frontend").pod_affinity(
                "zone", match_labels={"app": "backend"}
            )
        elif r < 0.5:
            b = b.label("app", "solo").pod_anti_affinity(
                "kubernetes.io/hostname", match_labels={"app": "solo"}
            )
        elif r < 0.7:
            b = b.label("app", "web").preferred_pod_affinity(
                int(rng.integers(1, 100)), "zone", match_labels={"app": "backend"}
            )
        else:
            b = b.label("app", "plain")
        pods.append(b.obj())
    assert_parity(nodes, pods, placed)
