"""Scheduler-extender webhook server — the delivery boundary of SURVEY.md
§8.2: a kube-scheduler configured with this extender sends its
filter/prioritize/preempt/bind verbs here and the TPU framework answers.

Wire shapes are byte-compatible with
staging/src/k8s.io/kube-scheduler/extender/v1/types.go:
- POST /filter     ExtenderArgs{pod, nodes|nodenames} ->
                   ExtenderFilterResult{nodes|nodenames, failedNodes,
                   failedAndUnresolvableNodes, error}
- POST /prioritize ExtenderArgs -> HostPriorityList [{host, score 0..10}]
                   (MaxExtenderPriority; the caller multiplies by the
                   extender weight and rescales vs MaxNodeScore)
- POST /preempt    ExtenderPreemptionArgs{pod, nodeNameToVictims|
                   nodeNameToMetaVictims} -> ExtenderPreemptionResult
                   {nodeNameToMetaVictims: {node: {pods: [{uid}],
                   numPDBViolations}}}
- POST /bind       ExtenderBindingArgs{podName, podNamespace, podUID, node}
                   -> ExtenderBindingResult{error}
- GET  /metrics    prometheus exposition (reference names)
- GET  /healthz /livez /readyz

Filter and prioritize answer from the DEVICE by default: concurrent webhook
requests micro-batch into one vmapped filter+score evaluation
(solver/evaluate.py) whose pipeline is shared with the exact solver, so the
served verdicts are bit-identical to an in-process solve over the same
snapshot. ``backend="oracle"`` retains the scalar NumPy path for parity
tests. The server also exposes an ingest surface (the apiserver-shaped
CRUD the extender's watch connection would provide in a reference
deployment) so `cli.py serve` is an operable component:
- POST   /api/nodes           Node dict or {"items": [...]} (create/update)
- DELETE /api/nodes/{name}
- POST   /api/pods            Pod dict or {"items": [...]}
- DELETE /api/pods/{ns}/{name}
- GET    /api/state           {"nodes": N, "pods": P, "unscheduled": U}
- GET    /api/leases          {"items": [coordination.k8s.io Lease, ...]}
In ``--mode scheduler`` a full Scheduler drains the queue in the
background: ingested pods get bound by device solves without any external
kube-scheduler (the cmd/kube-scheduler#Run analog).

Handlers are pure dict->dict functions (golden-JSON testable, SURVEY §8.6)
wrapped by a thin aiohttp app. The server holds a ClusterState for the pod
side of NodeInfo (an extender keeps its own watch-fed view in the reference
deployment; ExtenderArgs only carries Node objects). nodeCacheCapable mode
accepts/returns bare node names resolved against that state.
"""

from __future__ import annotations

import time
from typing import Mapping, Sequence

import numpy as np

from ..api.objects import Node, Pod
from ..ops.oracle import preemption as opr
from ..ops.oracle.profile import FullOracle, make_oracle_nodes
from ..state.cluster import ApiError, ClusterState
from .. import metrics

MAX_EXTENDER_PRIORITY = 10


class DecodeError(Exception):
    """Per-request decode failure inside a micro-batch: the HTTP layer maps
    it to a 500 for that one request without failing its batch-mates."""


class ExtenderCore:
    """Verb implementations as pure dict->dict handlers.

    backend="device" (default): filter/prioritize scores come from one
    vmapped device evaluation per request group. backend="oracle": scalar
    NumPy reference path (the sanitizer, SURVEY §8.6).
    """

    def __init__(
        self,
        cluster: ClusterState,
        node_cache_capable: bool = False,
        backend: str = "device",
        solver_config=None,
        tracer=None,
    ):
        self.cluster = cluster
        self.node_cache_capable = node_cache_capable
        self.backend = backend
        # obs span layer (kubernetes_tpu/obs): shared with the embedded
        # Scheduler in --mode scheduler so webhook evaluation spans and
        # solve spans land in one flight recorder; a disabled tracer
        # otherwise (one attribute check per request group)
        if tracer is None:
            from ..obs import Tracer

            tracer = Tracer(enabled=False)
        self.tracer = tracer
        if backend == "device":
            from ..solver.evaluate import BatchEvaluator

            self.evaluator = BatchEvaluator(solver_config)
        else:
            self.evaluator = None

    # -- helpers --

    def _pods_by_node(self) -> dict[str, list[Pod]]:
        out: dict[str, list[Pod]] = {}
        for p in self.cluster.list_pods():
            if p.node_name:
                out.setdefault(p.node_name, []).append(p)
        return out

    def _resolve_nodes(self, args: Mapping) -> tuple[list[Node], bool, list[str]]:
        """(nodes, by_name, unknown_names): honor nodes vs nodenames
        (nodeCacheCapable). Unknown names fail per-node, not per-request —
        the extender's watch-fed view may lag the scheduler's."""
        if args.get("nodenames") is not None:
            nodes, unknown = [], []
            for n in args["nodenames"]:
                try:
                    nodes.append(self.cluster.get_node(n))
                except ApiError:
                    unknown.append(n)
            return nodes, True, unknown
        items = (args.get("nodes") or {}).get("items") or []
        return [Node.from_dict(d) for d in items], False, []

    def _oracle(self, nodes: list[Node]) -> FullOracle:
        pods_by_node = self._pods_by_node()
        return FullOracle(make_oracle_nodes(nodes, pods_by_node))

    # per-webhook-batch device evaluation path: ktpu: hot
    def _score_rows(
        self, pods: Sequence[Pod], nodes: list[Node]
    ) -> np.ndarray:
        """[len(pods), len(nodes)] int32 full-pipeline totals, -1 =
        infeasible — one device call for the whole pod group."""
        if self.backend == "device":
            with self.cluster.lock:  # one consistent snapshot of the view
                pods_by_node = self._pods_by_node()
                services = self.cluster.list_services()
                pvs = self.cluster.list_pvs()
                pvcs = self.cluster.list_pvcs()
            return self.evaluator.evaluate(
                list(pods),
                nodes,
                pods_by_node,
                services=services,
                pvs=pvs,
                pvcs=pvcs,
            )
        oracle = self._oracle(nodes)
        rows = np.full((len(pods), len(nodes)), -1, dtype=np.int32)
        for pi, pod in enumerate(pods):
            feasible = oracle.feasible_set(pod)
            totals = oracle.score_totals(pod, feasible)
            for i in feasible:
                rows[pi, i] = totals[i]
        return rows

    # -- verbs --

    def filter(self, args: Mapping) -> dict:
        return self.run_many([("filter", args)])[0]

    def prioritize(self, args: Mapping) -> list[dict]:
        """HostPriorityList: full-pipeline totals rescaled into the 0..10
        extender score range (MaxExtenderPriority). Decode errors raise —
        the HTTP layer turns them into a 500 so the caller sees the failure
        instead of silently dropping this extender's scores."""
        out = self.run_many([("prioritize", args)])[0]
        if isinstance(out, DecodeError):
            raise KeyError(str(out))
        return out

    def run_many(self, requests: list[tuple[str, Mapping]]) -> list:
        """Evaluate a micro-batch of filter/prioritize requests. Requests
        sharing one node list (the common case: kube-scheduler fans a batch
        of pods over the same snapshot) share a single device evaluation —
        the pod axis of the vmap. Responses keep request order. A request
        that fails to decode gets a per-request error (filter: the wire's
        {"error"} shape; prioritize: a DecodeError the HTTP layer turns
        into a 500 for that request alone) — it never poisons the batch."""
        # cross-process trace propagation: a request carrying the obs
        # layer's traceContext (the outbound client attaches it per
        # batch) pins this evaluation span to the CALLER's trace, so a
        # webhook round trip appears inside the scheduling batch's
        # trace instead of as an anonymous server-side event
        tctx = next(
            (
                args["traceContext"]
                for _verb, args in requests
                if isinstance(args, Mapping)
                and isinstance(args.get("traceContext"), Mapping)
            ),
            None,
        )
        attrs = {"requests": len(requests)}
        trace_id = None
        if tctx is not None:
            trace_id = tctx.get("trace")
            for k in ("parent", "replica", "incarnation"):
                if tctx.get(k) is not None:
                    attrs[k] = tctx[k]
        with self.tracer.span(
            "extender_batch", trace_id=trace_id, **attrs
        ):
            return self._run_many(requests)

    def _run_many(self, requests: list[tuple[str, Mapping]]) -> list:
        import hashlib
        import json

        results: list = [None] * len(requests)
        # group key -> [(req_idx, verb, pod)]; key captures everything the
        # evaluation depends on: mode, resolved names, per-request unknown
        # names, and (full-node mode) the node payload itself — two requests
        # naming the same nodes with different capacities must not share
        groups: dict[tuple, list] = {}
        meta: dict[tuple, tuple] = {}
        for ri, (verb, args) in enumerate(requests):
            try:
                pod = Pod.from_dict(args["pod"])
                nodes, by_name, unknown = self._resolve_nodes(args)
            except Exception as e:  # any decode failure stays per-request
                if verb == "filter":
                    results[ri] = {"error": str(e)}
                else:
                    results[ri] = DecodeError(str(e))
                continue
            if by_name:
                payload_key = ""
            else:
                payload_key = hashlib.blake2b(
                    json.dumps(
                        (args.get("nodes") or {}).get("items") or [],
                        sort_keys=True,
                    ).encode(),
                    digest_size=16,
                ).hexdigest()
            key = (
                by_name,
                tuple(n.name for n in nodes),
                tuple(unknown),
                payload_key,
            )
            if key not in groups:
                groups[key] = []
                meta[key] = (nodes, by_name, unknown)
            groups[key].append((ri, verb, pod))
        for key, members in groups.items():
            nodes, by_name, unknown = meta[key]
            rows = self._score_rows([pod for _, _, pod in members], nodes)
            for (ri, verb, pod), row in zip(members, rows):
                if verb == "filter":
                    results[ri] = self._filter_result(
                        row, nodes, by_name, unknown
                    )
                else:
                    results[ri] = self._prioritize_result(row, nodes)
        return results

    def _filter_result(
        self, row: np.ndarray, nodes: list[Node], by_name: bool,
        unknown: list[str],
    ) -> dict:
        passed: list[Node] = []
        failed: dict[str, str] = {}
        for i, node in enumerate(nodes):
            if row[i] >= 0:
                passed.append(node)
            else:
                failed[node.name] = "node did not satisfy filters"
        out: dict = {
            "failedNodes": failed,
            "failedAndUnresolvableNodes": {
                n: "node not found" for n in unknown
            },
        }
        if by_name:
            out["nodenames"] = [n.name for n in passed]
        else:
            out["nodes"] = {"items": [n.to_dict() for n in passed]}
        return out

    def _prioritize_result(
        self, row: np.ndarray, nodes: list[Node]
    ) -> list[dict]:
        mx = int(row.max()) if row.size else -1
        return [
            {
                "host": n.name,
                "score": (
                    MAX_EXTENDER_PRIORITY * int(row[i]) // mx
                    if mx > 0 and row[i] >= 0
                    else 0
                ),
            }
            for i, n in enumerate(nodes)
        ]

    def preempt(self, args: Mapping) -> dict:
        try:
            pod = Pod.from_dict(args["pod"])
        except KeyError as e:
            return {"error": str(e)}
        from ..ops.oracle import plugins as opl

        pods_by_node = self._pods_by_node()
        pdbs = self.cluster.list_pdbs()
        candidates = args.get("nodeNameToVictims") or args.get(
            "nodeNameToMetaVictims"
        ) or {}
        # static gate: preemption cannot resolve taints/affinity/
        # nodeName/unschedulable failures (the dry-run is fit-only) —
        # never offer such nodes
        live: list = []
        for node_name in candidates:
            try:
                node = self.cluster.get_node(node_name)
            except ApiError:
                continue
            if (
                opl.node_name_filter(pod, node)
                and opl.node_unschedulable_filter(pod, node)
                and opl.taint_toleration_filter(pod, node)
                and opl.node_affinity_filter(pod, node)
            ):
                live.append(node)

        if self.backend == "device" and live:
            victims_map = self._preempt_device(pod, live, pods_by_node, pdbs)
        else:
            victims_map = {}
            for node in live:
                nv = opr.select_victims_on_node(
                    pod,
                    node.allocatable,
                    node.allowed_pod_number,
                    pods_by_node.get(node.name, []),
                    pdbs,
                )
                if nv is None:
                    continue  # dropped from the result = not a candidate
                victims_map[node.name] = (list(nv.victims), nv.num_violating)

        out: dict[str, dict] = {}
        for node_name, (victims, n_viol) in victims_map.items():
            if self.node_cache_capable:
                out[node_name] = {
                    "pods": [{"uid": v.uid or v.key} for v in victims],
                    "numPDBViolations": n_viol,
                }
            else:
                out[node_name] = {
                    "pods": [v.to_dict() for v in victims],
                    "numPDBViolations": n_viol,
                }
        # extender.go#ProcessPreemption reads NodeNameToMetaVictims only for
        # nodeCacheCapable extenders, NodeNameToVictims (full pods) otherwise
        if self.node_cache_capable:
            return {"nodeNameToMetaVictims": out}
        return {"nodeNameToVictims": out}

    def _preempt_device(
        self, pod: Pod, nodes: list[Node], pods_by_node, pdbs
    ) -> dict:
        """Device-backed /preempt (VERDICT r3 #8): ONE batched dry-run
        over all statically-feasible candidates instead of a scalar
        per-node loop — the in-process PostFilter's pre-screen behind the
        wire. Fit-only semantics identical to select_victims_on_node,
        including zero-victim fits: a node where the pod fits without
        eviction STAYS in the result with an empty victim list, exactly
        like the scalar path's NodeVictims([], 0). The vocab is built
        over the pod AND the candidate nodes so an extended resource the
        nodes don't advertise stays visible (fit then fails on its zero
        allocatable instead of being silently dropped)."""
        from ..solver.preemption import PreemptionEvaluator
        from ..tensorize.schema import ResourceVocab, build_node_batch

        if not hasattr(self, "_preemptor"):
            self._preemptor = PreemptionEvaluator()
        vocab = ResourceVocab.build([pod], nodes)
        batch = build_node_batch(nodes, vocab=vocab)
        placed_by_slot = {
            i: pods_by_node.get(nd.name, []) for i, nd in enumerate(nodes)
        }
        static_row = np.zeros(batch.padded, dtype=bool)
        static_row[: len(nodes)] = True  # static gate already applied
        return self._preemptor.victims_by_node(
            pod,
            batch,
            [nd.name for nd in nodes],
            placed_by_slot,
            static_row,
            pdbs,
            candidate_slots=list(range(len(nodes))),
        )

    def bind(self, args: Mapping) -> dict:
        try:
            self.cluster.bind(
                args.get("podNamespace") or "default",
                args["podName"],
                args["node"],
            )
            return {}
        except (KeyError, ApiError) as e:
            return {"error": str(e)}


class MicroBatcher:
    """Coalesce concurrent filter/prioritize requests into one device call.

    Requests arriving within ``window`` seconds ride one ExtenderCore
    .run_many() (executed off the event loop). The analog of the reference's
    in-proc 16-way parallel-for: here parallelism is the vmap pod axis."""

    def __init__(self, core: ExtenderCore, window: float = 0.002):
        self.core = core
        self.window = window
        self._pending: list = []
        self._task = None

    async def submit(self, verb: str, args: Mapping):
        import asyncio

        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((verb, args, fut))
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._drain())
        return await fut

    async def _drain(self):
        import asyncio

        # loop until no request arrived while the previous batch was in the
        # executor — submit() only spawns a new task when this one is done,
        # so returning with _pending non-empty would strand those futures
        # ktpu: ignore[RETRY001]: batch pump, not a retry loop — a failed batch FAILS its futures (nothing replayed) and the sleep is the fixed micro-batch window cadence, so jitter would be wrong
        while True:
            await asyncio.sleep(self.window)
            batch, self._pending = self._pending, []
            if not batch:
                return
            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            try:
                results = await loop.run_in_executor(
                    None,
                    self.core.run_many,
                    [(verb, args) for verb, args, _ in batch],
                )
            except Exception as e:
                for _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            metrics.extender_batch_size.observe(len(batch))
            metrics.extender_request_seconds.observe(time.perf_counter() - t0)
            for (_, _, fut), res in zip(batch, results):
                if fut.done():
                    continue
                if isinstance(res, DecodeError):
                    fut.set_exception(res)
                else:
                    fut.set_result(res)


def _load_state_file(cluster: ClusterState, path: str) -> None:
    """Initial-state ingest: JSON/YAML with {"nodes": [...], "pods": [...],
    "services": [...], "pdbs": [...], "resourceSlices": [...],
    "deviceClasses": [...], "resourceClaims": [...]} of wire-shape dicts."""
    import json

    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        import yaml

        doc = yaml.safe_load(text)
    for nd in doc.get("nodes") or []:
        cluster.create_node(Node.from_dict(nd))
    for pd in doc.get("pods") or []:
        cluster.create_pod(Pod.from_dict(pd))
    if doc.get("services"):
        from ..api.objects import Service

        for sd in doc["services"]:
            cluster.create_service(Service.from_dict(sd))
    if doc.get("pdbs"):
        from ..api.objects import PodDisruptionBudget

        for dd in doc["pdbs"]:
            cluster.create_pdb(PodDisruptionBudget.from_dict(dd))
    if (
        doc.get("resourceSlices")
        or doc.get("deviceClasses")
        or doc.get("resourceClaims")
    ):
        from ..api.dra import DeviceClass, ResourceClaim, ResourceSlice

        for sd in doc.get("resourceSlices") or []:
            cluster.create_resource_slice(ResourceSlice.from_dict(sd))
        for cd in doc.get("deviceClasses") or []:
            cluster.create_device_class(DeviceClass.from_dict(cd))
        for cd in doc.get("resourceClaims") or []:
            cluster.create_resource_claim(ResourceClaim.from_dict(cd))


def make_app(
    core: ExtenderCore,
    scheduler=None,
    batch_window: float = 0.002,
    recorder=None,
    slo=None,
):
    """aiohttp application wiring the pure handlers to the wire.

    With ``scheduler`` (a Scheduler over the same ClusterState), a
    background task drains the queue: ingested pods are bound by device
    solves — serve --mode scheduler. ``recorder`` (an
    obs.FlightRecorder, defaulting to the scheduler's) backs the
    ``/debug/flightrecorder`` and ``/debug/spans`` endpoints; ``slo``
    (an obs.SloEngine, defaulting to the scheduler's) backs
    ``GET /debug/slo`` — the live are-we-meeting-SLOs answer. The
    scheduler's flight telemetry (obs.Telemetry, serve --telemetry)
    backs ``GET /debug/profile`` — per-stage profile + sentinel state,
    with ``?capture=1`` forcing a manual replay-bundle capture."""
    import asyncio

    from aiohttp import web

    batcher = MicroBatcher(core, window=batch_window)

    async def _json(request):
        return await request.json()

    async def filter_(request):
        return web.json_response(
            await batcher.submit("filter", await _json(request))
        )

    async def prioritize(request):
        try:
            return web.json_response(
                await batcher.submit("prioritize", await _json(request))
            )
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def preempt(request):
        return web.json_response(core.preempt(await _json(request)))

    async def bind(request):
        return web.json_response(core.bind(await _json(request)))

    async def metrics_(request):
        return web.Response(
            body=metrics.render(), content_type="text/plain"
        )

    async def healthz(request):
        return web.Response(text="ok")

    # -- flight recorder / span debug surface (kubernetes_tpu/obs) --

    if recorder is None and scheduler is not None:
        recorder = getattr(scheduler, "flight", None)

    async def debug_flightrecorder(request):
        if recorder is None:
            return web.json_response(
                {"error": "observability disabled (serve --obs)"},
                status=404,
            )
        # one snapshot backs both the response and the optional disk
        # dump (?dump=1), so the two can never diverge; plain GETs (a
        # poller) don't touch the disk
        snap = recorder.snapshot()
        if request.query.get("dump"):
            snap["dumped_to"] = recorder.dump(
                trigger="manual", snapshot=snap
            )
        return web.json_response(snap)

    async def debug_spans(request):
        if recorder is None:
            return web.json_response(
                {"error": "observability disabled (serve --obs)"},
                status=404,
            )
        return web.json_response({"spans": recorder.spans()})

    # -- live SLO surface (kubernetes_tpu/obs/slo.py) --

    if slo is None and scheduler is not None:
        slo = getattr(scheduler, "slo", None)

    async def debug_slo(request):
        if slo is None:
            return web.json_response(
                {"error": "SLO engine disabled (serve --slo)"},
                status=404,
            )
        return web.json_response(slo.snapshot())

    # -- flight telemetry surface (kubernetes_tpu/obs profiler +
    # sentinel + capture) --

    async def debug_profile(request):
        telemetry = (
            getattr(scheduler, "telemetry", None)
            if scheduler is not None
            else None
        )
        if telemetry is None:
            return web.json_response(
                {"error": "flight telemetry disabled (serve --telemetry)"},
                status=404,
            )
        snap = telemetry.snapshot()
        if request.query.get("capture"):
            # operator-triggered forensic capture: bundle the most
            # recent complete batch exactly as an anomaly would
            telemetry.capture("manual", note="GET /debug/profile?capture=1")
            snap = telemetry.snapshot()
            snap["captured"] = True
        return web.json_response(snap)

    # -- occupancy-hub HA surface (kubernetes_tpu/fleet) --

    async def debug_hub(request):
        status = None
        if scheduler is not None and scheduler.fleet is not None:
            from ..fleet.occupancy import ExchangeUnreachable

            try:
                status = scheduler.hub_status()
            except ExchangeUnreachable as e:
                # mid-blackout: every hub endpoint is down — exactly
                # what the operator polling this endpoint wants to know
                return web.json_response(
                    {"error": f"hub unreachable: {e}"}, status=503
                )
        if status is None:
            return web.json_response(
                {"error": "not a fleet replica (no occupancy hub)"},
                status=404,
            )
        return web.json_response(status)

    # -- ingest surface (the watch-fed view's write side) --

    def _items(doc):
        return doc["items"] if isinstance(doc, Mapping) and "items" in doc else [doc]

    async def post_nodes(request):
        doc = await _json(request)
        created = 0
        for nd in _items(doc):
            node = Node.from_dict(nd)
            try:
                core.cluster.create_node(node)
            except ApiError:
                core.cluster.update_node(node)
            created += 1
        return web.json_response({"applied": created})

    async def delete_node(request):
        try:
            core.cluster.delete_node(request.match_info["name"])
        except ApiError as e:
            return web.json_response({"error": e.reason}, status=404)
        return web.json_response({})

    async def post_pods(request):
        doc = await _json(request)
        created = 0
        for pd in _items(doc):
            pod = Pod.from_dict(pd)
            try:
                core.cluster.create_pod(pod)
            except ApiError:
                core.cluster.update_pod(pod)
            created += 1
        return web.json_response({"applied": created})

    async def delete_pod(request):
        try:
            core.cluster.delete_pod(
                request.match_info["ns"], request.match_info["name"]
            )
        except ApiError as e:
            return web.json_response({"error": e.reason}, status=404)
        return web.json_response({})

    async def get_state(request):
        pods = core.cluster.list_pods()
        return web.json_response(
            {
                "nodes": len(core.cluster.list_nodes()),
                "pods": len(pods),
                "unscheduled": sum(1 for p in pods if not p.node_name),
                "resourceVersion": core.cluster.resource_version,
            }
        )

    async def get_leases(request):
        # coordination.k8s.io wire shapes: who leads (leader election)
        return web.json_response(
            {"items": [le.to_dict() for le in core.cluster.list_leases()]}
        )

    app = web.Application()
    app.router.add_post("/filter", filter_)
    app.router.add_post("/prioritize", prioritize)
    app.router.add_post("/preempt", preempt)
    app.router.add_post("/bind", bind)
    app.router.add_get("/metrics", metrics_)
    for route in ("/healthz", "/livez", "/readyz"):
        app.router.add_get(route, healthz)
    app.router.add_get("/debug/flightrecorder", debug_flightrecorder)
    app.router.add_get("/debug/spans", debug_spans)
    app.router.add_get("/debug/slo", debug_slo)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_get("/debug/hub", debug_hub)
    app.router.add_post("/api/nodes", post_nodes)
    app.router.add_delete("/api/nodes/{name}", delete_node)
    app.router.add_post("/api/pods", post_pods)
    app.router.add_delete("/api/pods/{ns}/{name}", delete_pod)
    app.router.add_get("/api/state", get_state)
    app.router.add_get("/api/leases", get_leases)

    if scheduler is not None:

        async def drain(app):
            loop = asyncio.get_running_loop()

            async def loop_task():
                import logging
                import random

                log = logging.getLogger("kubernetes_tpu.serve")
                log.info("scheduler drain loop running")
                failures = 0
                while True:
                    progressed = False
                    if scheduler.pending:
                        try:
                            # bounded double-buffered burst: overlaps each
                            # batch's device read with the next batch's
                            # tensorize/dispatch (Scheduler.run_pipelined),
                            # then returns to the event loop so ingest
                            # keeps flowing
                            results = await loop.run_in_executor(
                                None,
                                lambda: scheduler.run_pipelined(
                                    max_batches=64
                                ),
                            )
                        except Exception:
                            # a failed burst must not kill the drain loop —
                            # log and retry (pods stay queued). Full-jitter
                            # backoff: a fixed sleep re-hammers a hub that
                            # is mid-failover in lockstep with every other
                            # replica's drain loop
                            failures += 1
                            log.exception("pipelined drain burst failed")
                            await asyncio.sleep(
                                random.uniform(
                                    0.0,
                                    min(1.0 * 2 ** (failures - 1), 30.0),
                                )
                            )
                            continue
                        failures = 0
                        progressed = any(
                            r.progressed for r in results
                        )
                    if not progressed:
                        # pending may count backoff/unschedulable pods the
                        # pop yields nothing for — don't busy-spin on them
                        await asyncio.sleep(0.02)

            task = asyncio.create_task(loop_task())
            yield
            task.cancel()

        app.cleanup_ctx.append(drain)
    return app


def run_server(
    cluster: ClusterState,
    host: str = "127.0.0.1",
    port: int = 10259,
    node_cache_capable: bool = False,
    mode: str = "extender",
    state_file: str | None = None,
    solver_config=None,
    grpc_port: int = 0,
    scheduler_config=None,
) -> None:
    """Blocking server entry (the cmd/kube-scheduler#Run analog serves
    healthz+metrics on 10259). mode="scheduler" also runs the batching
    scheduler loop over the ingested state; grpc_port > 0 additionally
    serves the bulk tensor gRPC path (SURVEY §6.8)."""
    import logging

    from aiohttp import web

    log = logging.getLogger("kubernetes_tpu.serve")
    if state_file:
        _load_state_file(cluster, state_file)
    scheduler = None
    tracer = recorder = None
    obs_cfg = getattr(scheduler_config, "obs", None)
    if mode == "scheduler":
        from ..scheduler import Scheduler

        scheduler = Scheduler(cluster, scheduler_config)
        tracer, recorder = scheduler.obs, scheduler.flight
    elif obs_cfg is not None:
        # extender-only mode still gets webhook spans + debug endpoints
        from ..obs import build_obs

        tracer, _journal, recorder = build_obs(obs_cfg)
    core = ExtenderCore(
        cluster, node_cache_capable, solver_config=solver_config,
        tracer=tracer,
    )
    log.info(
        "serving on %s:%d", host, port,
        extra={
            "mode": mode,
            "grpc_port": grpc_port,
            "observability": bool(recorder),
        },
    )
    grpc_server = None
    if grpc_port:
        from .bulk import serve_bulk

        grpc_server = serve_bulk(
            cluster, port=grpc_port, solver_config=solver_config,
            tracer=tracer,
        )
    app = make_app(core, scheduler=scheduler, recorder=recorder)
    try:
        web.run_app(app, host=host, port=port)
    finally:
        if grpc_server is not None:
            grpc_server.stop(grace=1.0)
