"""Convex-relaxation mega-planner (ISSUE 19): the continuous-assignment
engine in solver/relax.py and everything wired to it.

Pinned here at tier-1 scale:

1. the relaxed+rounded plan is FEASIBLE — no resource or pod-count
   overcommit, static masks honored — on abundant, overloaded, and
   adversarial scarce/fragmented shapes (the rounding clamp is the
   load-bearing piece: the fractional optimum routinely overcommits
   before it);
2. rounding-repair parity: the full relax -> round -> auction-repair
   plan survives the sequential oracle's feasibility replay
   (``FullOracle.validate_feasible`` — every placed pick in the
   feasible set given identical history), and its placement count
   clears 0.95x the oracle's own greedy run;
3. dual prices: ~zero on an uncontended cluster, positive where
   demand exceeds capacity, exported per node group in sorted order;
4. planner routing (rebalance/planner.py): auto flips to the
   relaxation at the cell threshold, explicit engines pass through,
   unknown engines raise;
5. warm-start plumbing: ``PriorityQueue.reorder_active`` permutes
   ONLY within a priority band (priority stays the primary key),
   drops stale entries, and refuses custom-``less`` queues;
   ``Scheduler.drain_backlog(warm_start=True)`` ranks the backlog,
   reports relax counters, and does not regress the drain's
   chain_fraction or completeness;
6. index-headroom audit at the 2M-pod x 200k-node mega-plan shape:
   every flattened-index product the relaxation builds fits its
   dtype, and shapes that would overflow raise ``IndexWidthError``
   BEFORE anything is allocated.
"""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.budget import (
    IndexWidthError,
    assert_index_headroom,
    relax_estimate,
)
from kubernetes_tpu.solver.relax import RelaxConfig, RelaxSolver, group_prices
from kubernetes_tpu.solver.single_shot import SingleShotConfig
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.tensorize.plugins import build_static_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)
from kubernetes_tpu.utils.clock import FakeClock

ZONE = "topology.kubernetes.io/zone"


def solve_relax(nodes, pods, repair=True, **cfg):
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    solver = RelaxSolver(
        RelaxConfig(**cfg),
        repair=SingleShotConfig() if repair else None,
    )
    a = solver.solve(nbatch, pbatch, static)
    return np.asarray(a), solver.last, nbatch


def check_feasible(nodes, pods, assignments):
    """Every placement respects allocatable + pod-count + schedulability."""
    used = {n.name: {} for n in nodes}
    count = {n.name: 0 for n in nodes}
    for pod, a in zip(pods, assignments):
        if a < 0:
            continue
        node = nodes[a]
        assert not node.unschedulable
        count[node.name] += 1
        for k, v in pod.resource_request().items():
            used[node.name][k] = used[node.name].get(k, 0) + v
    for n in nodes:
        assert count[n.name] <= n.allowed_pod_number, n.name
        for k, v in used[n.name].items():
            assert v <= n.allocatable.get(k, 0), (n.name, k)


def mk_nodes(n, cpu="8", mem="32Gi", pods="20", zone_count=3):
    return [
        MakeNode()
        .name(f"n{i:03}")
        .capacity({"cpu": cpu, "memory": mem, "pods": pods})
        .label(ZONE, f"z{i % zone_count}")
        .obj()
        for i in range(n)
    ]


def mk_pods(n, cpu="500m", mem="1Gi", prio=None):
    out = []
    for i in range(n):
        b = MakePod().name(f"p{i:04}").req({"cpu": cpu, "memory": mem})
        if prio is not None:
            b = b.priority(prio[i % len(prio)])
        out.append(b.obj())
    return out


# -- 1. feasibility ------------------------------------------------------


def test_all_place_when_capacity_suffices():
    nodes = mk_nodes(8)
    pods = mk_pods(64)
    a, stats, _ = solve_relax(nodes, pods)
    assert all(x >= 0 for x in a)
    check_feasible(nodes, pods, a)
    assert stats.placed_total == 64
    assert stats.iterations >= 1


def test_no_overcommit_under_structural_overload():
    # demand ~4x capacity: the fractional optimum overcommits every
    # node before rounding — the clamp must hold the integral plan
    nodes = mk_nodes(4, pods="10")
    pods = mk_pods(160, cpu="1")
    a, stats, _ = solve_relax(nodes, pods)
    check_feasible(nodes, pods, a)
    placed = int((a >= 0).sum())
    assert placed < 160  # structurally impossible to place all
    # work conservation: capacity is 4 nodes x 8 cpu = 32 one-cpu pods
    assert placed >= 28


def test_rounding_clamp_without_repair_still_feasible():
    nodes = mk_nodes(4, pods="10")
    pods = mk_pods(120, cpu="1")
    a, stats, _ = solve_relax(nodes, pods, repair=False)
    check_feasible(nodes, pods, a)
    assert stats.repaired_pods == 0


def test_static_mask_honored():
    nodes = mk_nodes(4)
    nodes += [
        MakeNode()
        .name("tainted")
        .capacity({"cpu": "64", "memory": "256Gi", "pods": "110"})
        .taint("dedicated", "gpu", "NoSchedule")
        .obj()
    ]
    pods = mk_pods(40)
    a, _, _ = solve_relax(nodes, pods)
    check_feasible(nodes, pods, a)
    # the tainted node is by far the biggest — the relaxation would
    # love it, the static mask must keep every pod off it
    tainted = len(nodes) - 1
    assert not any(x == tainted for x in a)


# -- 2. rounding-repair parity vs the oracle -----------------------------


def _oracle_replay(nodes, pods, assigned, nbatch):
    names = [
        nbatch.names[a] if 0 <= a < nbatch.num_nodes else None
        for a in assigned
    ]
    oracle = FullOracle(make_oracle_nodes(nodes))
    return oracle.validate_feasible(
        pods, [int(a) for a in assigned], names=names
    )


def test_scarce_plan_passes_oracle_feasibility_replay():
    # scarce: demand 2x capacity, mixed priorities and pod sizes
    rng = np.random.default_rng(7)
    nodes = mk_nodes(12, pods="12")
    pods = []
    for i in range(180):
        cpu = int(rng.integers(2, 9)) * 250
        pods.append(
            MakePod()
            .name(f"p{i:04}")
            .req({"cpu": f"{cpu}m", "memory": "1Gi"})
            .priority(int(rng.integers(0, 8)))
            .obj()
        )
    a, _, nbatch = solve_relax(nodes, pods)
    errors = _oracle_replay(nodes, pods, a, nbatch)
    assert not errors, "\n".join(errors[:5])


def test_fragmented_plan_passes_oracle_feasibility_replay():
    # fragmented: a few big nodes among many small ones, pods that
    # only fit the big ones mixed with filler — a rounding bug that
    # ignores per-node residuals lands big pods on small nodes
    nodes = [
        MakeNode()
        .name(f"small{i:02}")
        .capacity({"cpu": "2", "memory": "4Gi", "pods": "8"})
        .label(ZONE, f"z{i % 3}")
        .obj()
        for i in range(10)
    ] + [
        MakeNode()
        .name(f"big{i}")
        .capacity({"cpu": "32", "memory": "128Gi", "pods": "60"})
        .label(ZONE, f"z{i}")
        .obj()
        for i in range(2)
    ]
    pods = mk_pods(24, cpu="3", mem="12Gi") + mk_pods(
        40, cpu="250m", mem="512Mi"
    )
    # builders above reuse names — rename the filler to keep keys unique
    pods = pods[:24] + [
        MakePod()
        .name(f"filler{i:03}")
        .req({"cpu": "250m", "memory": "512Mi"})
        .obj()
        for i in range(40)
    ]
    a, _, nbatch = solve_relax(nodes, pods)
    check_feasible(nodes, pods, a)
    errors = _oracle_replay(nodes, pods, a, nbatch)
    assert not errors, "\n".join(errors[:5])
    # every big pod that placed sits on a big node
    for p, x in zip(pods[:24], a[:24]):
        if x >= 0:
            assert nodes[x].name.startswith("big"), nodes[x].name


def test_objective_ratio_vs_greedy_anchor():
    rng = np.random.default_rng(11)
    nodes = mk_nodes(16, pods="16")
    pods = []
    for i in range(200):
        cpu = int(rng.integers(1, 7)) * 250
        pods.append(
            MakePod()
            .name(f"p{i:04}")
            .req({"cpu": f"{cpu}m", "memory": "1Gi"})
            .priority(int(rng.integers(0, 5)))
            .obj()
        )
    a, _, _ = solve_relax(nodes, pods)
    anchor, _ = FullOracle(make_oracle_nodes(nodes)).schedule(pods)
    relax_placed = int((a >= 0).sum())
    greedy_placed = sum(1 for x in anchor if x >= 0)
    assert relax_placed >= 0.95 * greedy_placed, (
        relax_placed,
        greedy_placed,
    )


# -- 3. dual prices ------------------------------------------------------


def test_dual_prices_zero_when_uncontended():
    nodes = mk_nodes(6)
    pods = mk_pods(6)
    _, stats, nbatch = solve_relax(nodes, pods)
    groups = [f"z{i % 3}" for i in range(nbatch.padded)]
    prices = group_prices(stats, groups, valid=nbatch.valid)
    assert set(prices) == {"z0", "z1", "z2"}
    assert all(v < 1e-3 for v in prices.values()), prices


def test_dual_prices_positive_under_contention_and_sorted():
    nodes = mk_nodes(6, pods="8")
    pods = mk_pods(120, cpu="1")
    _, stats, nbatch = solve_relax(nodes, pods)
    groups = [f"z{i % 3}" for i in range(nbatch.padded)]
    prices = group_prices(stats, groups, valid=nbatch.valid)
    assert list(prices) == sorted(prices)
    assert all(v > 0.0 for v in prices.values()), prices


# -- 4. planner routing --------------------------------------------------


def test_plan_engine_routing():
    from kubernetes_tpu.rebalance.planner import (
        RELAX_PLAN_CELLS,
        plan_engine,
    )

    assert plan_engine(1000, 128) == "auction"
    big_pods = RELAX_PLAN_CELLS // 1024
    assert plan_engine(big_pods, 1024) == "relax"
    assert plan_engine(10, 8, engine="relax") == "relax"
    assert plan_engine(10**9, 10**6, engine="auction") == "auction"
    with pytest.raises(ValueError):
        plan_engine(10, 8, engine="simplex")


# -- 5. warm-start plumbing ----------------------------------------------


def _queued(q):
    return [i.pod.name for i in q.pop_batch(100)]


def _qpod(name, prio=None):
    b = MakePod().name(name).req({"cpu": "100m"})
    if prio is not None:
        b = b.priority(prio)
    return b.obj()


def test_reorder_active_permutes_only_within_priority_band():
    clock = FakeClock()
    q = PriorityQueue(clock)
    for name, prio in [
        ("a", 1),
        ("b", 1),
        ("c", 1),
        ("hi", 9),
    ]:
        q.add(_qpod(name, prio))
        clock.advance(1)
    # the relaxed plan co-locates c and a (low ranks) — but hi keeps
    # popping first: priority stays the primary key
    ranked = q.reorder_active(
        {"default/c": 0, "default/a": 1, "default/hi": 2}
    )
    assert ranked == 3  # b is unranked (sorts after its ranked peers)
    assert _queued(q) == ["hi", "c", "a", "b"]


def test_reorder_active_refuses_custom_less():
    clock = FakeClock()
    q = PriorityQueue(clock, less=lambda x, y: x.pod.name < y.pod.name)
    q.add(_qpod("a"))
    assert q.reorder_active({"default/a": 0}) == 0


def test_reorder_active_drops_stale_entries():
    clock = FakeClock()
    q = PriorityQueue(clock)
    for name in ("a", "b", "c"):
        q.add(_qpod(name, 1))
        clock.advance(1)
    (popped,) = q.pop_batch(1)  # "a" leaves the active band
    assert popped.pod.name == "a"
    assert q.reorder_active({"default/c": 0, "default/b": 1}) == 2
    assert _queued(q) == ["c", "b"]


def _drain_setup(warm):
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState()
    for i in range(12):
        cs.create_node(
            MakeNode()
            .name(f"n{i:03}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .obj()
        )
    for i in range(96):
        cs.create_pod(
            MakePod()
            .name(f"pod-{i:04}")
            .req({"cpu": "100m", "memory": "256Mi"})
            .priority((0, 3, 7)[i % 3])
            .obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=16,
            solver=ExactSolverConfig(tie_break="first", group_size=8),
            backlog_warm_start=warm,
        ),
    )
    return cs, sched


def test_drain_warm_start_ranks_and_does_not_regress():
    cs_cold, cold = _drain_setup(warm=False)
    rep_cold = cold.drain_backlog(chunk_pods=16)
    cs_warm, warm = _drain_setup(warm=True)
    rep_warm = warm.drain_backlog(chunk_pods=16)
    # warm-start engaged: ranked pods, relax counters populated
    assert rep_cold.warm_start_ranked == 0
    assert rep_warm.warm_start_ranked >= 1
    assert rep_warm.relax_iterations >= 1
    # ...and is advisory-only: same completeness, no chain regression
    assert rep_warm.drained == rep_cold.drained == 96
    assert rep_warm.chain_fraction >= rep_cold.chain_fraction
    # every binding in the warm run is a real schedulable node
    for p in cs_warm.list_pods():
        assert p.node_name, p.key


def test_drain_warm_start_explicit_flag_overrides_config():
    _, sched = _drain_setup(warm=False)
    rep = sched.drain_backlog(chunk_pods=16, warm_start=True)
    assert rep.warm_start_ranked >= 1


# -- 6. index-headroom audit at the mega-plan shape ----------------------


def test_relax_estimate_2m_shape_has_headroom():
    est = relax_estimate(200_000, 2_000_000, rc=8)
    # the audit the solver runs before allocating anything
    assert_index_headroom(est.pod_pad, est.node_pad, rc_pad=est.rc_pad)
    # the flattened products the relaxation actually builds
    assert est.rc_pad * est.node_pad < 2**31  # rc*N cell table (int32)
    assert est.pod_pad * est.node_pad < 2**63
    # the rounding sort key: rc * 2^32 + rank stays below the 2^62
    # invalid sentinel for every real class id
    assert (est.rc_pad - 1) * (1 << 32) + est.pod_pad < 1 << 62
    # workspace factor inflates the raw resident set
    assert est.per_device_bytes >= est.sharded_bytes + est.replicated_bytes


@pytest.mark.parametrize(
    "nodes,pods,rc",
    [
        (1_000, 50_000, 8),
        (102_400, 512_000, 64),
        (200_000, 2_000_000, 8),
    ],
)
def test_headroom_property_flattened_products_fit(nodes, pods, rc):
    est = relax_estimate(nodes, pods, rc=rc)
    assert_index_headroom(est.pod_pad, est.node_pad, rc_pad=est.rc_pad)
    assert est.rc_pad * est.node_pad < 2**31
    assert (est.rc_pad - 1) * (1 << 32) + est.pod_pad < 1 << 62


def test_headroom_rejects_overflowing_rc_axis():
    with pytest.raises(IndexWidthError):
        # rc*N flat cell index would not fit int64
        assert_index_headroom(1_000, 2**30, rc_pad=2**33)


def test_headroom_rejects_sort_key_collision_with_sentinel():
    with pytest.raises(IndexWidthError):
        # a class id whose sort key would cross the 2^62 sentinel
        assert_index_headroom(1_000, 1_000, rc_pad=1 << 31)
