"""Compiled-path smoke test for the Pallas kernels on the real TPU (the
CPU test suite runs them in interpret mode only). Run:
    python scripts/pallas_smoke.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main() -> None:
    import jax

    from kubernetes_tpu.ops.pallas_kernels import (
        N_TILE,
        domain_counts_pallas,
        domain_counts_reference,
    )

    print(f"devices: {jax.devices()}")
    rng = np.random.default_rng(0)
    t, n, d_pad = 16, 20 * N_TILE, 32
    dom = rng.integers(-1, d_pad, size=(t, n)).astype(np.int32)
    cnt = rng.integers(0, 5, size=(t, n)).astype(np.int32)

    got = np.asarray(domain_counts_pallas(dom, cnt, d_pad))
    want = np.asarray(domain_counts_reference(dom, cnt, d_pad))
    np.testing.assert_array_equal(got, want)

    # timing: compiled kernel vs segment_sum lowering (device-resident)
    import jax.numpy as jnp

    dom_d, cnt_d = jnp.asarray(dom), jnp.asarray(cnt)
    ref_jit = jax.jit(domain_counts_reference, static_argnames=("d_pad",))
    for name, fn in (
        ("pallas", lambda: domain_counts_pallas(dom_d, cnt_d, d_pad)),
        ("segment_sum", lambda: ref_jit(dom_d, cnt_d, d_pad)),
    ):
        fn().block_until_ready()  # warm
        t0 = time.perf_counter()
        for _ in range(50):
            out = fn()
        out.block_until_ready()
        print(f"{name}: {(time.perf_counter() - t0) / 50 * 1e6:.0f}us/call")
    print("pallas smoke OK: compiled kernel matches reference")


if __name__ == "__main__":
    main()
