"""QueueingHints + leftover flush (VERDICT r2 #7): the
isPodWorthRequeuing gate (scheduling_queue.go) — fit-shaped events wake
only pods the changed node could now admit — and the 5-minute forced
flush running from schedule_batch."""

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock


def _sched(cs, clock):
    return Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )


def _park_two_blocked_pods(cs, sched):
    """One CPU-blocked pod, one memory-blocked pod; both end up parked."""
    cs.create_pod(MakePod().name("cpu-blocked").req({"cpu": "8"}).obj())
    cs.create_pod(
        MakePod().name("mem-blocked").req({"cpu": "1", "memory": "64Gi"}).obj()
    )
    r = sched.schedule_batch()
    assert len(r.unschedulable) == 2
    assert sched.queue.pending_counts()["unschedulable"] == 2


def test_cpu_only_node_update_does_not_wake_memory_blocked_pod():
    clock = FakeClock()
    cs = ClusterState()
    node = MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    cs.create_node(node)
    sched = _sched(cs, clock)
    _park_two_blocked_pods(cs, sched)

    # grow ONLY cpu: 2 -> 16; memory unchanged
    bigger = MakeNode().name("n").capacity(
        {"cpu": "16", "memory": "4Gi", "pods": "10"}
    ).obj()
    cs.update_node(bigger)
    counts = sched.queue.pending_counts()
    # cpu-blocked woke (now fits); mem-blocked stayed parked
    assert counts["unschedulable"] == 1
    clock.advance(2.0)  # past the retry backoff
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/cpu-blocked") == "n"
    assert sched.queue.pending_counts()["unschedulable"] == 1


def test_node_add_wakes_only_fitting_pods():
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("tiny").capacity({"cpu": "1", "memory": "1Gi", "pods": "10"}).obj()
    )
    sched = _sched(cs, clock)
    _park_two_blocked_pods(cs, sched)

    cs.create_node(
        MakeNode().name("cpu-big").capacity({"cpu": "32", "memory": "2Gi", "pods": "10"}).obj()
    )
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 1  # mem-blocked still parked


def test_label_change_wakes_everything():
    """A label change can unblock selector-filtered pods the fit hint knows
    nothing about — it must take the move-everything path."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    sched = _sched(cs, clock)
    cs.create_pod(
        MakePod().name("selective").req({"cpu": "1"}).node_selector({"tier": "gold"}).obj()
    )
    r = sched.schedule_batch()
    assert r.unschedulable
    relabeled = (
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"})
        .label("tier", "gold").obj()
    )
    cs.update_node(relabeled)
    assert sched.queue.pending_counts()["unschedulable"] == 0
    clock.advance(2.0)  # past the retry backoff
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/selective") == "n"


def test_assigned_pod_delete_wakes_fitting_pods_only():
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "4", "memory": "4Gi", "pods": "10"}).obj()
    )
    cs.create_pod(MakePod().name("occupant").req({"cpu": "4"}).obj())
    cs.bind("default", "occupant", "n")
    sched = _sched(cs, clock)
    _park_two_blocked_pods(cs, sched)  # cpu-blocked wants 8 (never fits n!)

    cs.delete_pod("default", "occupant")
    counts = sched.queue.pending_counts()
    # freed 4 cpu: cpu-blocked wants 8 -> still parked; mem-blocked wants
    # 64Gi -> still parked. Nothing fits, nothing wakes.
    assert counts["unschedulable"] == 2


def test_leftover_flush_from_schedule_batch():
    """Pods parked > 5 min force back into rotation on the next batch even
    with no event and no hint match."""
    clock = FakeClock()
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "10"}).obj()
    )
    sched = _sched(cs, clock)
    cs.create_pod(MakePod().name("stuck").req({"cpu": "8"}).obj())
    sched.schedule_batch()
    assert sched.queue.pending_counts()["unschedulable"] == 1

    clock.advance(301.0)
    r = sched.schedule_batch()  # flush moves it active; batch re-attempts it
    assert "default/stuck" in r.unschedulable  # re-tried (and re-parked)
