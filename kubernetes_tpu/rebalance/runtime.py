"""The rebalancer runtime: the background defragmentation loop both
scheduler loops tick when they go idle.

One ``Rebalancer`` per Scheduler incarnation. ``maybe_run`` is called
from the scheduling loops at cycle boundaries and is a no-op unless ALL
of: the interval elapsed, the queues are idle (no active/backoff work,
no in-flight solves, no Permit waiters — rebalancing never competes
with real scheduling work), the incarnation still holds its commit
fence (a zombie rebalancer can never move anything — checked here for
cheap skip AND enforced authoritatively by the eviction subresource),
and the snapshot actually looks fragmented.

Execution is deliberately thin: the rebalancer only EVICTS (through
``ClusterState.evict`` — Conflict-on-stale, PDB-enforcing, fenced) with
a nominated-node hint toward the auction's target; the evicted pod
re-enters the ordinary scheduling queue and the existing solve/assume/
bind path performs the migration with every constraint and safety check
it always applies. A migration the hint can't satisfy (capacity raced
away, constraints) lands wherever the solver places it — strictly no
new commit path.

Fleet scope: a replica's cache IS its shard (shard-filtered informer),
so the snapshot, the movable set, and therefore every eviction are
naturally scoped to nodes this replica owns.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

from .. import metrics
from ..state.cluster import ApiError
from .detector import detect
from .planner import plan_moves, select_moves


@dataclass(frozen=True)
class RebalanceConfig:
    # seconds between rebalance passes (checked on the scheduler clock,
    # so sim runs pace on virtual time)
    interval_s: float = 60.0
    # max-churn budget: evictions per rebalance cycle
    max_moves_per_cycle: int = 512
    # dominant-resource packed-utilization threshold below which the
    # in-use nodes count as fragmented (detector.py)
    min_packing: float = 0.7
    # minimum strict packing-score improvement (percent points) a move
    # must deliver; > 0 guarantees the cycle-over-cycle potential
    # argument that keeps repeated rebalancing from thrashing
    min_gain: int = 1
    # carry the auction target as a nominated-node hint on the evicted
    # pod (the solve then prefers it); off = plain requeue
    nominate: bool = True
    # planning engine: "auction" | "relax" | "auto" (route by shape —
    # see rebalance/planner.plan_engine; churn-budget-sized candidate
    # lists stay on the auction, mega shapes take the relaxation)
    plan_engine: str = "auto"


@dataclass(frozen=True)
class RunRecord:
    """One rebalance pass, for the sim invariants and the bench."""

    t: float  # clock.now() at the pass
    packing_before: float  # detector's packed utilization at the pass
    stranded_before: float
    planned: int  # raw auction diff size
    selected: int  # after budget/gain/feasibility/PDB bounding
    evicted: int  # evictions that actually landed
    pdb_blocked: int
    plan_solve_s: float  # the auction plan wall time


class Rebalancer:
    def __init__(self, config: RebalanceConfig | None, clock) -> None:
        self.config = config or RebalanceConfig()
        self.clock = clock
        self.history: list[RunRecord] = []
        # pod key -> target node of an executed eviction whose re-bind
        # has not been observed yet; reconcile() settles them
        self.pending_migrations: dict[str, str] = {}
        self.migrations_completed = 0
        self.migrations_to_target = 0
        self._last_run = float("-inf")

    # -- bookkeeping --

    def reconcile(self, cluster) -> None:
        """Settle pending migrations against cluster truth: an evicted
        pod that re-bound completes its migration (to the nominated
        target or elsewhere — both count; the hint is advisory); a pod
        deleted while migrating just drops out."""
        if not self.pending_migrations:
            return
        for key in sorted(self.pending_migrations):
            target = self.pending_migrations[key]
            ns, name = key.split("/", 1)
            try:
                pod = cluster.get_pod(ns, name)
            except ApiError:
                del self.pending_migrations[key]
                continue
            if pod.node_name:
                del self.pending_migrations[key]
                self.migrations_completed += 1
                to_target = pod.node_name == target
                if to_target:
                    self.migrations_to_target += 1
                metrics.rebalance_migrations_total.labels(
                    "target" if to_target else "elsewhere"
                ).inc()

    def stats(self) -> dict:
        cfg = self.config
        evicted = [r.evicted for r in self.history]
        return {
            "runs": len(self.history),
            "evicted": sum(evicted),
            "max_cycle_evictions": max(evicted, default=0),
            "over_budget": sum(
                1 for e in evicted if e > cfg.max_moves_per_cycle
            ),
            "budget": cfg.max_moves_per_cycle,
            "pdb_blocked": sum(r.pdb_blocked for r in self.history),
            "migrations_completed": self.migrations_completed,
            "migrations_to_target": self.migrations_to_target,
        }

    # -- the pass --

    @staticmethod
    def _movable(scheduler, pod) -> bool:
        """A bound pod the rebalancer may migrate: owned by one of this
        scheduler's profiles, bind confirmed (not mid-assume), and
        plain-shaped — ports/spread/interpod/volume/DRA pods are out of
        the auction's scoring scope (solver/single_shot.py), so their
        placements are never judged movable. Conservative by design:
        the rebalancer only touches pods whose improvement it can
        actually compute.

        Pod-group members are co-movable-or-not: migrating one member
        alone would break the gang's co-placement, and the auction
        re-places pods individually, so gang pods are conservatively
        never movable (the whole gang moves only via eviction + a fresh
        atomic gang solve, which the rebalancer does not drive)."""
        from ..gang import GANG_LABEL

        if GANG_LABEL in pod.labels:
            return False
        if pod.scheduler_name not in scheduler.solvers:
            return False
        if scheduler.cache.is_assumed(pod.key):
            return False
        if pod.host_ports() or pod.topology_spread_constraints:
            return False
        if pod.affinity is not None and (
            pod.affinity.pod_affinity is not None
            or pod.affinity.pod_anti_affinity is not None
        ):
            return False
        if pod.pvc_names:
            return False
        if pod.resource_claim_names or pod.claim_templates_unresolved:
            return False
        return True

    def _gather(self, scheduler, batch):
        """Drain-candidate selection: walk the in-use nodes EMPTIEST
        first (lowest dominant-resource fill) and collect their movable
        pods up to the churn budget — those are the pods the auction
        re-places this cycle, and their source slots are masked out of
        the plan so consolidation pushes off them. Within a partially
        drained source the least-important pods go first. The returned
        fixed load is the cluster's live usage minus the candidates'
        own requests. Runs under the cluster lock."""
        from .detector import packing_score

        vocab = batch.vocab
        sources: list[tuple[int, str, int, list]] = []
        for name in sorted(scheduler.cache.nodes):
            info = scheduler.cache.nodes[name]
            if info.node is None or not info.pods:
                continue
            try:
                slot = scheduler.snapshot.slot_of(name)
            except KeyError:
                continue
            pods_here = [
                info.pods[key]
                for key in sorted(info.pods)
                if self._movable(scheduler, info.pods[key])
            ]
            if not pods_here:
                continue
            sources.append(
                (packing_score(batch, slot), name, slot, pods_here)
            )
        sources.sort(key=lambda s: (s[0], s[1]))  # emptiest first

        budget = self.config.max_moves_per_cycle
        packing_bar = int(self.config.min_packing * 100)
        movable: list[tuple[object, int]] = []
        drain_slots: set[int] = set()
        fixed_used = batch.used.copy()
        fixed_cnt = batch.pod_count.copy()
        # never drain the FULLEST in-use node (the plan needs at least
        # one loaded consolidation target), and never drain a node
        # already at the packing bar — it is where pods should land
        for _fill, _name, slot, pods_here in sources[:-1]:
            if len(movable) >= budget or _fill >= packing_bar:
                break
            take = sorted(
                pods_here,
                key=lambda p: (
                    p.effective_priority, -p.start_time, p.key,
                ),
            )[: budget - len(movable)]
            drain_slots.add(slot)
            for pod in take:
                movable.append((pod, slot))
                req = np.asarray(
                    vocab.vectorize(pod.resource_request()),
                    dtype=np.int64,
                )
                fixed_used[:, slot] = np.maximum(
                    fixed_used[:, slot] - req, 0
                )
                fixed_cnt[slot] = max(int(fixed_cnt[slot]) - 1, 0)
        return movable, fixed_used, fixed_cnt, frozenset(drain_slots)

    def maybe_run(self, scheduler, res) -> int:
        """One conditional rebalance pass; returns evictions executed
        (0 = nothing happened). ``res`` is the cycle's BatchResult —
        evictions land in ``res.rebalance_evictions`` so drive loops
        count the pass as forward progress."""
        cfg = self.config
        now = self.clock.now()
        if now - self._last_run < cfg.interval_s:
            return 0
        cluster = scheduler.cluster
        with cluster.lock:
            self.reconcile(cluster)
            counts = scheduler.queue.pending_counts()
            if (
                counts["active"]
                or counts["backoff"]
                or scheduler._waiting
                or scheduler._in_flight
            ):
                return 0  # real work pending; retry next idle cycle
            self._last_run = now
            if (
                scheduler._fence_role is not None
                and not cluster.fence_valid(
                    scheduler._fence_role, scheduler._fence_token
                )
            ):
                # zombie incarnation: the eviction subresource would
                # reject each move anyway — skip the whole pass
                metrics.rebalance_runs_total.labels("fenced").inc()
                scheduler._log.warning(
                    "rebalance pass skipped: commit fence for role %r "
                    "is no longer valid (zombie incarnation)",
                    scheduler._fence_role,
                    extra={"step": scheduler._trace_step},
                )
                return 0
        step = scheduler._trace_step
        with scheduler.obs.span(
            "rebalance", trace_id=step, **scheduler._span_tags
        ) as rsp:
            with cluster.lock:
                batch = scheduler.snapshot.update(scheduler.cache)
                # cheap signal FIRST: on a healthy cluster the pass
                # ends here, before the node walk / pod scans /
                # request vectorizing the gather pays — the idle tick
                # is just the snapshot refresh plus host numpy
                report = detect(batch, min_packing=cfg.min_packing)
                if not report.fragmented:
                    movable = []
                else:
                    movable, fixed_used, fixed_cnt, drain_slots = (
                        self._gather(scheduler, batch)
                    )
                    slot_names = list(scheduler.snapshot.names)
                    # Node object per snapshot slot: the plan auction
                    # folds nodeSelector/affinity/taints through the
                    # production static builder so a constrained pod
                    # is never planned toward an infeasible target
                    slot_nodes = [
                        (
                            scheduler.cache.nodes[nm].node
                            if nm in scheduler.cache.nodes
                            else None
                        )
                        if nm
                        else None
                        for nm in slot_names
                    ]
                    pdbs = cluster.list_pdbs()
                    # advisory signal: pending pods more important
                    # than the LEAST important bound pod anywhere —
                    # re-packing could seat them. One pod walk, the
                    # baseline hoisted (this runs under the lock).
                    lowest_bound = None
                    pending_prios = []
                    for p in cluster.list_pods():
                        if p.node_name:
                            if (
                                lowest_bound is None
                                or p.effective_priority < lowest_bound
                            ):
                                lowest_bound = p.effective_priority
                        else:
                            pending_prios.append(p.effective_priority)
                    inversions = (
                        sum(
                            1
                            for pr in pending_prios
                            if pr > lowest_bound
                        )
                        if lowest_bound is not None
                        else 0
                    )
                    report = replace(
                        report, priority_inversions=inversions
                    )
                    metrics.rebalance_priority_inversions.set(
                        inversions
                    )
            metrics.rebalance_packing_utilization.set(
                report.packed_utilization
            )
            metrics.rebalance_stranded_fraction.set(
                report.stranded_fraction
            )
            rsp.set(
                packing=round(report.packed_utilization, 4),
                nodes_in_use=report.nodes_in_use,
                movable=len(movable),
                inversions=report.priority_inversions,
            )
            if not report.fragmented or not movable:
                metrics.rebalance_runs_total.labels(
                    "not_fragmented"
                ).inc()
                return 0
            # the plan solve runs OUTSIDE the cluster lock (same
            # discipline as the scheduling loops: the device never
            # blocks ingest); expect_rv at evict time catches anything
            # that moved meanwhile
            t0 = self.clock.perf()
            with scheduler.obs.span(
                "rebalance_plan", trace_id=step, pods=len(movable),
            ):
                raw = plan_moves(
                    batch, movable, fixed_used, fixed_cnt,
                    drain_slots, slot_nodes=slot_nodes,
                    engine=cfg.plan_engine,
                )
            plan_solve_s = self.clock.perf() - t0
            metrics.rebalance_plan_seconds.observe(plan_solve_s)
            plan = select_moves(
                batch, slot_names, raw, pdbs,
                budget=cfg.max_moves_per_cycle,
                min_gain=cfg.min_gain,
            )
            if plan.pdb_blocked:
                metrics.rebalance_pdb_blocked_total.inc(
                    plan.pdb_blocked
                )
            evicted = 0
            if plan.moves:
                fence = (
                    (scheduler._fence_role, scheduler._fence_token)
                    if scheduler._fence_role is not None
                    else None
                )
                with cluster.lock, scheduler.obs.span(
                    "rebalance_evict", trace_id=step,
                    moves=len(plan.moves),
                ):
                    cycle = scheduler.queue.scheduling_cycle
                    for mv in plan.moves:
                        try:
                            cluster.evict(
                                mv.pod.namespace,
                                mv.pod.name,
                                expect_rv=mv.pod.resource_version,
                                fence=fence,
                                nominated_node=(
                                    mv.target if cfg.nominate else ""
                                ),
                            )
                        except ApiError as e:
                            if e.fenced:
                                # fenced mid-pass: the incarnation just
                                # lost its lease — stop moving anything
                                scheduler._log.warning(
                                    "rebalance pass fenced mid-"
                                    "execution after %d eviction(s)",
                                    evicted,
                                    extra={"step": step},
                                )
                                break
                            continue  # raced (rv/PDB/deleted): skip
                        evicted += 1
                        metrics.rebalance_evictions_total.inc()
                        self.pending_migrations[mv.pod.key] = mv.target
                        res.rebalance_evictions.append(
                            (mv.pod.key, mv.source, mv.target)
                        )
                        if scheduler.journal is not None:
                            scheduler.journal.record(
                                step, cycle, mv.pod,
                                "evicted_for_rebalance",
                                node=mv.source,
                                nominated=mv.target,
                                reason=(
                                    "rebalance: packing gain "
                                    f"+{mv.gain} (cluster packed "
                                    f"utilization "
                                    f"{report.packed_utilization:.2f})"
                                ),
                            )
            self.history.append(
                RunRecord(
                    t=now,
                    packing_before=report.packed_utilization,
                    stranded_before=report.stranded_fraction,
                    planned=plan.planned,
                    selected=len(plan.moves),
                    evicted=evicted,
                    pdb_blocked=plan.pdb_blocked,
                    plan_solve_s=plan_solve_s,
                )
            )
            metrics.rebalance_runs_total.labels(
                "planned" if evicted else "empty_plan"
            ).inc()
            rsp.set(
                planned=plan.planned,
                selected=len(plan.moves),
                evicted=evicted,
            )
        return evicted
