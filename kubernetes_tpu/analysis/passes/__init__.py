"""The analyzer's rule set.

Two tiers since Analyzer v2:

- **ALL_PASSES** — per-module passes (one file at a time, the PR 1
  engine): cheap scoping passes first, cross-file MET001 last.
- **ALL_PROJECT_PASSES** — project passes, run ONCE over the whole
  analyzed set against the cross-module call graph
  (:mod:`..project`): lock-order deadlock detection, fence and retry
  discipline, cross-module host-sync escape, metrics-doc drift.
"""

from __future__ import annotations

from .hostsync import HostSyncPass
from .tracedbranch import TracedBranchPass
from .dtypes import DtypeDisciplinePass
from .locks import LockDisciplinePass
from .metricnames import MetricNamePass
from .lockorder import LockOrderPass
from .fence import FencePass
from .retry import RetryPass
from .xsync import CrossModuleSyncPass
from .metricsdoc import MetricsDocPass

ALL_PASSES = (
    HostSyncPass,
    TracedBranchPass,
    DtypeDisciplinePass,
    LockDisciplinePass,
    MetricNamePass,
)

ALL_PROJECT_PASSES = (
    LockOrderPass,
    FencePass,
    RetryPass,
    CrossModuleSyncPass,
    MetricsDocPass,
)

__all__ = [
    "ALL_PASSES",
    "ALL_PROJECT_PASSES",
    "HostSyncPass",
    "TracedBranchPass",
    "DtypeDisciplinePass",
    "LockDisciplinePass",
    "MetricNamePass",
    "LockOrderPass",
    "FencePass",
    "RetryPass",
    "CrossModuleSyncPass",
    "MetricsDocPass",
]
