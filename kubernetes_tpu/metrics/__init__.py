"""Scheduler metrics with the reference's metric names
(pkg/scheduler/metrics/metrics.go, SURVEY.md §6.5) so existing dashboards
port, plus TPU-solve-specific series.

Uses prometheus_client against a dedicated registry (the [BOUNDARY]
equivalent of component-base metrics/legacyregistry); `render()` emits the
exposition text the /metrics endpoint serves.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

REGISTRY = CollectorRegistry()

_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0,
    10.0, 20.0,
)

# -- reference names (pkg/scheduler/metrics) --

schedule_attempts_total = Counter(
    "scheduler_schedule_attempts_total",
    "Number of attempts to schedule pods, by result.",
    ["result", "profile"],
    registry=REGISTRY,
)
scheduling_attempt_duration_seconds = Histogram(
    "scheduler_scheduling_attempt_duration_seconds",
    "Scheduling attempt latency (scheduling algorithm + binding).",
    ["result", "profile"],
    buckets=_BUCKETS,
    registry=REGISTRY,
)
pod_scheduling_attempts = Histogram(
    "scheduler_pod_scheduling_attempts",
    "Number of attempts to successfully schedule a pod.",
    buckets=(1, 2, 4, 8, 16),
    registry=REGISTRY,
)
pod_scheduling_sli_duration_seconds = Histogram(
    "scheduler_pod_scheduling_sli_duration_seconds",
    "E2e latency for a pod being scheduled, from first queue add.",
    ["attempts"],
    buckets=_BUCKETS,
    registry=REGISTRY,
)
framework_extension_point_duration_seconds = Histogram(
    "scheduler_framework_extension_point_duration_seconds",
    "Latency for running all plugins of an extension point.",
    ["extension_point", "status", "profile"],
    buckets=_BUCKETS,
    registry=REGISTRY,
)
plugin_execution_duration_seconds = Histogram(
    "scheduler_plugin_execution_duration_seconds",
    "Duration for running a plugin at a specific extension point.",
    ["plugin", "extension_point", "status"],
    buckets=_BUCKETS,
    registry=REGISTRY,
)
pending_pods = Gauge(
    "scheduler_pending_pods",
    "Pending pods, by queue (active|backoff|unschedulable|gated).",
    ["queue"],
    registry=REGISTRY,
)
queue_incoming_pods_total = Counter(
    "scheduler_queue_incoming_pods_total",
    "Number of pods added to scheduling queues by event and queue type.",
    ["queue", "event"],
    registry=REGISTRY,
)
preemption_attempts_total = Counter(
    "scheduler_preemption_attempts_total",
    "Total preemption attempts in the cluster.",
    registry=REGISTRY,
)
fold_cache_total = Counter(
    "scheduler_plugin_fold_cache_total",
    "Out-of-tree plugin fold results served from the per-batch memo "
    "cache vs recomputed (result=hit|miss).",
    ["result"],
    registry=REGISTRY,
)
preemption_victims = Histogram(
    "scheduler_preemption_victims",
    "Number of selected preemption victims.",
    buckets=(1, 2, 4, 8, 16, 32, 64),
    registry=REGISTRY,
)

# -- TPU-solve specific (SURVEY §6.5 additions) --

solve_latency_seconds = Histogram(
    "scheduler_tpu_solve_latency_seconds",
    "Device solve wall time per batch.",
    buckets=_BUCKETS,
    registry=REGISTRY,
)
solve_batch_size = Histogram(
    "scheduler_tpu_solve_batch_size",
    "Pods per device solve.",
    buckets=(1, 8, 32, 128, 512, 1024, 4096, 16384, 65536),
    registry=REGISTRY,
)
tensorize_seconds = Histogram(
    "scheduler_tpu_tensorize_seconds",
    "Host-side tensorization time per batch.",
    buckets=_BUCKETS,
    registry=REGISTRY,
)
solves_discarded_total = Counter(
    "scheduler_tpu_solves_discarded_total",
    "Deferred device solves discarded by the pipelined loop's conflict "
    "fence (a capacity/mask-affecting event landed between dispatch and "
    "apply); the batch's pods retry immediately without backoff.",
    registry=REGISTRY,
)
pipeline_fallback_total = Counter(
    "scheduler_pipeline_fallback_total",
    "Times the pipelined loop fell back to a synchronous (fence-free) "
    "cycle after consecutive fence discards — the livelock backstop "
    "under sustained capacity/mask-affecting event churn.",
    registry=REGISTRY,
)
pipeline_mode_total = Counter(
    "scheduler_pipeline_mode_total",
    "Popped batches by dispatch mode: overlap (plain fit shapes "
    "dispatched before the previous solve's read lands), carry (hard "
    "shapes — ports/spread/interpod/volumes/DRA/nominated/multi-"
    "profile — drained-then-chained through the occupancy-carrying "
    "sub-batch split), stream (the streaming dispatcher's unified "
    "device-resident solve loop, run_streaming), sync (livelock-"
    "backstop / degraded-mode synchronous cycle).",
    ["mode"],
    registry=REGISTRY,
)
stream_depth = Gauge(
    "scheduler_stream_depth",
    "Dispatched-but-unapplied stream slots in the streaming "
    "dispatcher's bounded work ring (run_streaming); bounded by "
    "SchedulerConfig.stream_depth.",
    registry=REGISTRY,
)
stream_inflight_reads = Gauge(
    "scheduler_stream_inflight_reads",
    "Deferred assignment reads handed to the streaming dispatcher's "
    "completion thread and not yet landed (the async D2H transfers "
    "currently hiding tunnel RTT off the driver thread).",
    registry=REGISTRY,
)
stream_unhidden_reads_total = Counter(
    "scheduler_stream_unhidden_reads_total",
    "Streaming-dispatcher assignment reads that actually BLOCKED the "
    "driver thread (> 1 ms) — the un-hidden tunnel round trips the "
    "device-resident solve loop exists to eliminate. Steady state "
    "should trend toward one per event-fence, not one per batch.",
    registry=REGISTRY,
)
stream_slot_discard_total = Counter(
    "scheduler_stream_slot_discard_total",
    "Stream slots discarded by the per-slot fence epochs (a "
    "conflicting/occupancy event landed between a slot's dispatch and "
    "its apply): only the affected slot and its chained successors "
    "die; unrelated slots apply normally.",
    registry=REGISTRY,
)
pipeline_subbatches_total = Counter(
    "scheduler_pipeline_subbatches_total",
    "Chained sub-batch solves dispatched by the RTT-hiding batch split "
    "(run_pipelined): sub-batch i's assignment read overlaps sub-batch "
    "i+1's device solve.",
    registry=REGISTRY,
)
batch_failure_total = Counter(
    "scheduler_batch_failure_total",
    "Batched solves that failed before applying, by reason "
    "(tensorize|dispatch|read|corrupt) — each failure requeues or "
    "bisects the batch through the resilience ladder instead of "
    "silently dropping it, and journals a non-terminal solver_error "
    "per pod.",
    ["reason"],
    registry=REGISTRY,
)
solve_tier = Gauge(
    "scheduler_tpu_solve_tier",
    "Fallback-ladder tier the profile's solves currently dispatch at "
    "(0 = the top tier; higher = more degraded, last = pure-host "
    "serial greedy).",
    ["profile"],
    registry=REGISTRY,
)
breaker_state = Gauge(
    "scheduler_tpu_breaker_state",
    "Solve circuit-breaker state per profile "
    "(0 closed | 1 open | 2 half-open probe).",
    ["profile"],
    registry=REGISTRY,
)
breaker_transitions_total = Counter(
    "scheduler_tpu_breaker_transitions_total",
    "Solve circuit-breaker transitions, by kind "
    "(rebuild|trip|probe|reclose).",
    ["transition"],
    registry=REGISTRY,
)
fallback_solves_total = Counter(
    "scheduler_tpu_fallback_solves_total",
    "Batches solved below the top ladder tier, by tier "
    "(single|cpu|host).",
    ["tier"],
    registry=REGISTRY,
)
quarantined_pods_total = Counter(
    "scheduler_tpu_quarantined_pods_total",
    "Pods quarantined by poison-batch bisection: the solve fails "
    "deterministically at every ladder tier only when this pod is in "
    "the batch.",
    registry=REGISTRY,
)
quarantine_readmits_total = Counter(
    "scheduler_tpu_quarantine_readmits_total",
    "Quarantined pods re-admitted to the scheduling queue after their "
    "TTL'd backoff elapsed.",
    registry=REGISTRY,
)
# -- gang scheduling (kubernetes_tpu/gang) --

gang_commits_total = Counter(
    "scheduler_gang_commits_total",
    "Pod groups committed atomically: every solved member bound in one "
    "all-or-nothing bind_gang call.",
    registry=REGISTRY,
)
gang_bound_pods_total = Counter(
    "scheduler_gang_bound_pods_total",
    "Pods bound as members of an atomic gang commit.",
    registry=REGISTRY,
)
gang_incomplete_total = Counter(
    "scheduler_gang_incomplete_total",
    "Gang rounds released without a commit: a member failed, a fence "
    "discarded a sub-solve, or the atomic bind was rejected — every "
    "staged placement rolled back and the gang requeued (a partial "
    "gang is never bound).",
    registry=REGISTRY,
)
gang_quarantined_total = Counter(
    "scheduler_gang_quarantined_total",
    "Pod groups quarantined as a unit: the quorum never assembled "
    "before the min-member timeout, or consecutive released rounds hit "
    "the configured limit.",
    registry=REGISTRY,
)
gang_assembly_seconds = Histogram(
    "scheduler_gang_assembly_seconds",
    "Time from a gang's first appearance at the pop gate to its atomic "
    "commit (time-to-full-gang).",
    buckets=_BUCKETS,
    registry=REGISTRY,
)

mesh_devices = Gauge(
    "scheduler_mesh_devices",
    "Devices in the node-axis solve mesh the scheduler dispatches "
    "against (SchedulerConfig.mesh_devices; 1 = the unsharded "
    "single-device path).",
    registry=REGISTRY,
)
h2d_bytes_total = Counter(
    "scheduler_tpu_host_to_device_bytes_total",
    "Host->device bytes uploaded by ExactSolver.solve: per-pod packed "
    "arrays, per-batch occupancy rows, dirty-column heals, class-table "
    "cache misses, and full session (re)uploads.",
    registry=REGISTRY,
)
d2h_bytes_total = Counter(
    "scheduler_tpu_device_to_host_bytes_total",
    "Device->host bytes downloaded by ExactSolver.solve: the per-batch "
    "assignment vector in session mode, the packed result buffer in "
    "standalone mode.",
    registry=REGISTRY,
)

# -- backlog drain (Scheduler.drain_backlog, ISSUE 12) --

backlog_chunks_total = Counter(
    "scheduler_backlog_chunks_total",
    "Chunk-aligned sub-batches a backlog drain dispatched through the "
    "streaming ring (Scheduler.drain_backlog): the 512k-pod backlog "
    "cut into budget-sized chunks chained against the resident "
    "session.",
    registry=REGISTRY,
)
backlog_budget_splits_total = Counter(
    "scheduler_backlog_budget_splits_total",
    "Chunk halvings the HBM budget planner (solver/budget.py "
    "plan_chunk) took before the drain chunk fit the per-device "
    "budget — the auto-split that replaces an OOM mid-drain.",
    registry=REGISTRY,
)
backlog_drain_seconds = Histogram(
    "scheduler_backlog_drain_seconds",
    "End-to-end wall time of one Scheduler.drain_backlog pass "
    "(queue full -> backlog drained through the streaming ring).",
    buckets=(0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
    registry=REGISTRY,
)
backlog_hbm_estimated_bytes = Gauge(
    "scheduler_backlog_hbm_estimated_bytes",
    "The HBM budget model's predicted host->device upload bytes for "
    "the last backlog drain (solver/budget.py ShapeEstimate: fresh "
    "session + per-chunk uploads). Compare against "
    "scheduler_backlog_hbm_measured_bytes — the pair is what makes "
    "the capacity-planning model checkable in production.",
    registry=REGISTRY,
)
backlog_hbm_measured_bytes = Gauge(
    "scheduler_backlog_hbm_measured_bytes",
    "Measured scheduler_tpu_host_to_device_bytes_total delta across "
    "the last backlog drain — the ground truth the HBM budget "
    "model's estimate is validated against.",
    registry=REGISTRY,
)

# -- convex-relaxation mega-planner (solver/relax.py, ISSUE 19) --

relax_iterations = Histogram(
    "scheduler_relax_iterations",
    "Dual-ascent iterations one convex-relaxation solve ran before "
    "the residual early exit (solver/relax.py): converged plans stop "
    "well short of the max_iters budget; samples pinned at the budget "
    "mean the shape is contended past the tolerance.",
    buckets=(4, 8, 16, 32, 64, 128, 256, 512),
    registry=REGISTRY,
)
relax_residual = Gauge(
    "scheduler_relax_residual",
    "Final relative-overcommit residual of the last relaxation solve "
    "(max over nodes/resources of fractional load/capacity - 1, "
    "clipped at 0). 0 = the fractional plan fit everywhere; a "
    "persistent positive value is structural oversubscription the "
    "rounding clamp absorbs.",
    registry=REGISTRY,
)
relax_repair_rounds = Histogram(
    "scheduler_relax_repair_rounds",
    "Auction rounds the integrality-tail repair ran after rounding a "
    "relaxed plan (0 = the rounding seated everything or repair was "
    "disabled). Growth here means the relaxation is leaving more "
    "work to the sequential engine it exists to replace.",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64),
    registry=REGISTRY,
)
relax_dual_price = Gauge(
    "scheduler_relax_dual_price",
    "Converged per-node-group dual price of the last relaxation solve "
    "(mean over the group's nodes of sum_k lam[k,n] + mu[n], score "
    "points per normalized capacity unit) — the autoscaler cost "
    "signal (ROADMAP item #2): a group pinned at 0 has slack, a "
    "rising price is demand the group cannot absorb.",
    ["group"],
    registry=REGISTRY,
)

# -- closed-loop hot-path auto-tuning (kubernetes_tpu/tuning) --

tuning_adjustments_total = Counter(
    "scheduler_tuning_adjustments_total",
    "Auto-tuning controller decisions, by knob (backlog_chunk|"
    "stream_depth|pipeline_split|fleet_flush) and action (probe = try "
    "a neighbor value, accept = probe beat the incumbent by the "
    "hysteresis margin, revert = probe lost and the incumbent was "
    "restored, settle = both directions exhausted and the controller "
    "went inert, unsettle = a workload shift re-opened tuning).",
    ["knob", "action"],
    registry=REGISTRY,
)
tuning_knob_value = Gauge(
    "scheduler_tuning_knob_value",
    "Current value of each auto-tuned hot-path knob (the live setting "
    "the dispatch loops read; compare with scheduler_tuning_settled to "
    "tell a converged value from a mid-probe one).",
    ["knob"],
    registry=REGISTRY,
)
tuning_settled = Gauge(
    "scheduler_tuning_settled",
    "1 when the knob's controller has settled (neither direction "
    "improves past the hysteresis margin); 0 while measuring or "
    "probing.",
    ["knob"],
    registry=REGISTRY,
)
tuning_guardrail_rejections_total = Counter(
    "scheduler_tuning_guardrail_rejections_total",
    "Tuner proposals rejected by a hard guardrail BEFORE application "
    "— e.g. a drain-chunk candidate whose HBM budget-model estimate "
    "(solver/budget.py) exceeds the per-device budget. A rejection is "
    "the guardrail working; a tuner-applied value failing its guard "
    "would be a breach, which the sim invariant and bench ladder pin "
    "at zero.",
    ["knob"],
    registry=REGISTRY,
)
tuning_workload_shifts_total = Counter(
    "scheduler_tuning_workload_shifts_total",
    "Workload shifts the tuning runtime detected after settling (the "
    "CounterWindow signature moved past tuning.shiftThreshold): every "
    "settled controller re-opens and re-converges for the new "
    "regime.",
    registry=REGISTRY,
)

# -- crash-restart recovery + commit fencing --

restart_recovery_seconds = Histogram(
    "scheduler_restart_recovery_seconds",
    "Wall time of the cold-start recovery pass: rebuilding cache/queue "
    "from cluster truth, re-adopting pods a prior incarnation orphaned, "
    "rolling back half-committed occupancy (claim reservations, fleet "
    "pending rows), and journaling terminal 'recovered' records.",
    buckets=_BUCKETS,
    registry=REGISTRY,
)
commit_fenced_total = Counter(
    "scheduler_commit_fenced_total",
    "Bind commits rejected by the state service's fencing-token check: "
    "this incarnation's fence token was revoked (lease lost, partition, "
    "or a newer incarnation took over) — the zombie's commit never "
    "lands, extending the fleet admit-time ownership fence to bind "
    "time.",
    registry=REGISTRY,
)
watch_delivery_error_total = Counter(
    "scheduler_watch_delivery_error_total",
    "Exceptions raised by ClusterState watch subscribers during event "
    "delivery: caught and counted so one bad callback cannot prevent "
    "delivery to the remaining subscribers or corrupt the event "
    "sequence.",
    registry=REGISTRY,
)

# -- fleet tier (kubernetes_tpu/fleet) --

fleet_occupancy_row_age_seconds = Gauge(
    "scheduler_fleet_occupancy_row_age_seconds",
    "Staleness of the cross-shard occupancy view this replica admits "
    "against: age of the last successful hub fetch PLUS the oldest "
    "peer's liveness age inside it. Beyond FleetConfig.max_row_age_s "
    "admission "
    "turns conservative — cross-shard-constrained placements are "
    "rejected rather than risking overcommit on stale rows.",
    registry=REGISTRY,
)

fleet_replicas = Gauge(
    "scheduler_fleet_replicas",
    "Alive replicas in this replica's fleet membership view "
    "(fleet/membership.py; the configured universe is static).",
    registry=REGISTRY,
)
fleet_owned_nodes = Gauge(
    "scheduler_fleet_owned_nodes",
    "Nodes the ring partition currently assigns to this replica's "
    "shard (fleet/ring.py).",
    registry=REGISTRY,
)
fleet_resyncs_total = Counter(
    "scheduler_fleet_resyncs_total",
    "Shard resyncs: the partition moved (membership change or "
    "ring remap) and the replica rebuilt its shard-scoped cache and "
    "queue from cluster truth.",
    registry=REGISTRY,
)
fleet_occupancy_rows_total = Counter(
    "scheduler_fleet_occupancy_rows_total",
    "Occupancy-exchange row operations, by op "
    "(staged|committed|withdrawn|retired|handoff).",
    ["op"],
    registry=REGISTRY,
)
fleet_reconcile_conflicts_total = Counter(
    "scheduler_fleet_reconcile_conflicts_total",
    "Placements the cross-shard reconciliation rejected pre-assume, "
    "by constraint family (ownership|spread|anti|stale|cas — stale = "
    "conservative admission under an aged-out occupancy view, cas = "
    "sustained hub compare-and-stage contention or a fenced write); "
    "the pods retried through the ordinary requeue machinery.",
    ["constraint"],
    registry=REGISTRY,
)
fleet_admit_cas_conflict_total = Counter(
    "scheduler_fleet_admit_cas_conflict_total",
    "Cross-process atomic admits rejected by the hub's fenced "
    "compare-and-stage, by kind (version = the hub moved past the "
    "admitted view — a peer's row landed first, the replica re-fetches "
    "and re-admits; fenced = the replica's hub write privilege was "
    "revoked by a membership retire — no row lands until its forced "
    "resync re-registers it wholesale).",
    ["kind"],
    registry=REGISTRY,
)
fleet_hub_rpc_seconds = Histogram(
    "scheduler_fleet_hub_rpc_seconds",
    "Wall time of one occupancy-hub RPC from RemoteOccupancyExchange "
    "(the HubOp method on the bulk gRPC boundary), by hub op — the "
    "wire cost a cross-process fleet pays per stage/commit/view that "
    "an in-process fleet gets for a lock acquire.",
    ["op"],
    buckets=_BUCKETS,
    registry=REGISTRY,
)
hub_epoch = Gauge(
    "scheduler_hub_epoch",
    "The occupancy hub's fencing epoch as last observed by this "
    "process (hub side: the lease grant this hub serves under; client "
    "side: the highest epoch RemoteOccupancyExchange has verified on a "
    "HubOp reply — replies from a lower epoch are structurally "
    "ignored). Monotone per fleet; a step is a hub failover.",
    registry=REGISTRY,
)
hub_failover_total = Counter(
    "scheduler_hub_failover_total",
    "Hub failovers: a standby hub was promoted past epoch 1 (hub "
    "side), or RemoteOccupancyExchange observed the hub epoch advance "
    "and re-anchored on the new primary (client side — the replica "
    "then forces a wholesale resync republish, the dirty-heal path).",
    registry=REGISTRY,
)
hub_replication_lag_rows = Gauge(
    "scheduler_hub_replication_lag_rows",
    "Standby replication lag in op-log entries: the primary's latest "
    "opseq minus this standby's applied cursor at the last "
    "StandbyReplicator poll (0 = caught up; the failover loss window "
    "is bounded by this).",
    registry=REGISTRY,
)
fleet_flush_dedup_total = Counter(
    "scheduler_fleet_flush_dedup_total",
    "Write-behind flushes the hub dropped as duplicates: a retried "
    "apply_ops batch whose (client, flush_seq) key was already "
    "applied — the reply of the first attempt was lost after the "
    "server-side apply, and without the dedup its rows would "
    "double-stage and its journal lines double-append.",
    registry=REGISTRY,
)
fleet_drain_partitions = Gauge(
    "scheduler_fleet_drain_partitions",
    "Replica partitions in the active fleet backlog drain's ledger "
    "(drain_init): the hub-hosted coordinator ran the global relax "
    "plan once and split the backlog by planned-node shard ownership; "
    "each partition drains concurrently under its own drain lease.",
    registry=REGISTRY,
)
fleet_drain_residual_pods = Gauge(
    "scheduler_fleet_drain_residual_pods",
    "Pods in the fleet backlog drain's residual cohort: cross-shard-"
    "constrained (spread / anti-affinity), plan-unplaced, or planned "
    "onto an unowned node — drained SERIALIZED as one lease after "
    "every shard partition completes, so constraint correctness is "
    "never traded for parallelism. A large value means the partitioner "
    "is forfeiting the fleet speedup.",
    registry=REGISTRY,
)
fleet_drain_lease_reassignments_total = Counter(
    "scheduler_fleet_drain_lease_reassignments_total",
    "Drain leases reassigned after a holder died mid-drain: the hub "
    "retire returned the lease's outstanding keys to the orphan pool "
    "and a surviving replica claimed them (the no-pod-lost half of the "
    "drain ledger's exactly-once contract).",
    registry=REGISTRY,
)
fleet_drain_replica_seconds = Histogram(
    "scheduler_fleet_drain_replica_seconds",
    "Wall time one replica spent draining one claimed lease through "
    "its own drain_backlog slot ring (fleet_drain_backlog) — the "
    "per-replica denominator behind the fleet drain speedup.",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
             600.0),
    registry=REGISTRY,
)
fleet_mesh_slice_devices = Gauge(
    "scheduler_fleet_mesh_slice_devices",
    "Devices in this replica's EXCLUSIVE mesh slice "
    "(SchedulerConfig.mesh_slice = (rank, count): contiguous first-N "
    "partitioning of the visible device set, so N fleet replicas "
    "stream-dispatch against disjoint device sets). 0 = no slice "
    "configured (the sole-owner scheduler uses mesh_devices alone).",
    registry=REGISTRY,
)
bulk_retry_total = Counter(
    "scheduler_bulk_retry_total",
    "Transient bulk-gRPC call failures retried by BulkClient's "
    "bounded exponential backoff, by method.",
    ["method"],
    registry=REGISTRY,
)

# -- scheduling trace layer (kubernetes_tpu/obs) --

trace_spans_total = Counter(
    "scheduler_tpu_trace_spans_total",
    "Spans finished by the scheduling trace layer, by span name "
    "(schedule_batch|snapshot|tensorize|fold|dispatch|fence|apply|"
    "bind|enqueue|discard|extender_batch).",
    ["name"],
    registry=REGISTRY,
)
journal_records_total = Counter(
    "scheduler_tpu_trace_journal_records_total",
    "Per-pod decision-journal records written, by outcome "
    "(bound|unschedulable|bind_failure|permit_wait|permit_rejected|"
    "permit_timeout|discarded|solver_error|quarantined|recovered|"
    "evicted_for_rebalance|gang_incomplete|telemetry_anomaly).",
    ["outcome"],
    registry=REGISTRY,
)
flight_recorder_dumps_total = Counter(
    "scheduler_tpu_flight_recorder_dumps_total",
    "Flight-recorder ring dumps, by trigger "
    "(crash|invariant|manual|breaker).",
    ["trigger"],
    registry=REGISTRY,
)

# -- flight telemetry (kubernetes_tpu/obs/{profile,sentinel,bundle}) --

profile_stage_seconds = Counter(
    "scheduler_profile_stage_seconds",
    "Cumulative wall seconds attributed to each batch stage by the "
    "continuous per-stage profiler, by stage (tensorize|dispatch|"
    "fence_wait|deferred_read|validate|apply|bind). Assembled "
    "host-side from seams the loops already time — zero new device "
    "syncs; rate() it for the live stage mix.",
    ["stage"],
    registry=REGISTRY,
)
anomaly_total = Counter(
    "scheduler_anomaly_total",
    "Anomalies fired by the telemetry sentinel's multi-window "
    "regression rules, by signal (pods_per_sec|p99_latency_s|"
    "chain_fraction|discard_rate|cas_conflict_rate|"
    "gang_incomplete_rate|breaker). Each firing also journals a "
    "telemetry_anomaly record and arms a capture-on-anomaly replay "
    "bundle.",
    ["signal"],
    registry=REGISTRY,
)
telemetry_bundles_total = Counter(
    "scheduler_telemetry_bundles_total",
    "Capture-on-anomaly replay-bundle capture events, by trigger "
    "(sentinel|breaker|quarantine|invariant|manual). Counts the "
    "capture decision; whether a bundle directory was written "
    "additionally depends on a configured bundle dir and the "
    "per-process bundle budget.",
    ["trigger"],
    registry=REGISTRY,
)

# -- live SLO engine (kubernetes_tpu/obs/slo.py) --

slo_p50_pod_latency_seconds = Gauge(
    "scheduler_slo_p50_pod_latency_seconds",
    "Sliding-window median per-pod scheduling latency (first queue "
    "entry -> bind commit, the bench ladder's sustained-latency "
    "definition), computed by the live SLO engine from the latencies "
    "the apply path already materializes — zero new device syncs.",
    registry=REGISTRY,
)
slo_p99_pod_latency_seconds = Gauge(
    "scheduler_slo_p99_pod_latency_seconds",
    "Sliding-window p99 per-pod scheduling latency (first queue entry "
    "-> bind commit) from the live SLO engine — 'are we meeting the "
    "latency SLO right now' without a bench ladder run.",
    registry=REGISTRY,
)
slo_bind_throughput = Gauge(
    "scheduler_slo_bind_throughput_pods_per_second",
    "Pods bound per second over the SLO engine's sliding window "
    "(ratio of sums, the CounterWindow.rate discipline).",
    registry=REGISTRY,
)
slo_error_budget_burn = Gauge(
    "scheduler_slo_error_budget_burn",
    "Multi-window error-budget burn rate: (observed bad-event "
    "fraction) / (allowed bad fraction), where a bad event is a bound "
    "pod missing the latency objective or a bind failure. 1.0 burns "
    "the budget exactly at the sustainable rate; the short window "
    "catches fast burns, the long window slow ones.",
    ["window"],
    registry=REGISTRY,
)
slo_healthy = Gauge(
    "scheduler_slo_healthy",
    "1 while the SLO engine reads healthy; 0 while the short-window "
    "burn rate exceeds the degraded threshold (with the minimum event "
    "count met). The degraded-health signal the fleet handoff "
    "ordering (exchange degraded flag) and the resilience breaker "
    "(half-open probes deferred) consume.",
    registry=REGISTRY,
)

# -- compile observability (kubernetes_tpu/obs/compile.py) --

xla_compilations_total = Counter(
    "scheduler_xla_compilations_total",
    "XLA backend compilations observed by the process-wide compile "
    "watcher (jax.monitoring backend_compile events) — each one is a "
    "dispatch that paid a compile stall instead of a cache hit.",
    registry=REGISTRY,
)
xla_compile_seconds_total = Counter(
    "scheduler_xla_compile_seconds_total",
    "Cumulative wall seconds spent in XLA backend compilation, as "
    "observed by the compile watcher.",
    registry=REGISTRY,
)
xla_compile_cache_keys = Gauge(
    "scheduler_xla_compile_cache_keys",
    "Distinct compile scopes (dispatch shape/static fingerprints) "
    "this process has compiled for — the working-set size of the jit "
    "cache as the scheduler sees it.",
    registry=REGISTRY,
)
xla_recompilations = Gauge(
    "scheduler_xla_recompilations",
    "Compilations beyond the first per compile scope: a steady-state "
    "loop re-paying a compile for a shape it already compiled — the "
    "silent streaming-hot-path killer the known-shape regression test "
    "pins at zero. Pairs with scheduler_xla_compile_cache_keys.",
    registry=REGISTRY,
)

# -- fleet trace/journal aggregation (the cross-replica obs surface) --

fleet_journal_segments_total = Counter(
    "scheduler_fleet_journal_segments_total",
    "Bounded journal segments this replica shipped to the occupancy "
    "hub's append-only aggregation surface (piggybacked on the "
    "existing write-behind flush — no new RPC cadence).",
    registry=REGISTRY,
)
fleet_journal_lines_total = Counter(
    "scheduler_fleet_journal_lines_total",
    "Decision-journal lines this replica shipped to the hub's "
    "aggregation surface (obs explain --fleet reads the merged "
    "stream).",
    registry=REGISTRY,
)

# -- continuous rebalancer (kubernetes_tpu/rebalance) --

rebalance_runs_total = Counter(
    "scheduler_rebalance_runs_total",
    "Rebalance passes by outcome: planned (evictions executed), "
    "empty_plan (fragmented but no strictly-improving executable "
    "move survived bounding), not_fragmented (detector below "
    "threshold or nothing movable), fenced (the incarnation lost "
    "its commit fence — a zombie rebalancer moves nothing).",
    ["outcome"],
    registry=REGISTRY,
)
rebalance_evictions_total = Counter(
    "scheduler_rebalance_evictions_total",
    "Pods evicted by the rebalancer through the eviction "
    "subresource (each carries a nominated-node hint toward its "
    "auction target and re-enters the scheduling queue).",
    registry=REGISTRY,
)
rebalance_migrations_total = Counter(
    "scheduler_rebalance_migrations_total",
    "Completed migrations — an evicted pod re-bound — by where it "
    "landed (target = the auction's nominated node, elsewhere = the "
    "solver placed it differently; the hint is advisory).",
    ["result"],
    registry=REGISTRY,
)
rebalance_pdb_blocked_total = Counter(
    "scheduler_rebalance_pdb_blocked_total",
    "Planned moves dropped by the PDB gate "
    "(classify_pdb_violations over the selected stream): the pod's "
    "PodDisruptionBudget had no disruptions left.",
    registry=REGISTRY,
)
rebalance_plan_seconds = Histogram(
    "scheduler_rebalance_plan_seconds",
    "Wall time of the rebalance plan solve: the single-shot auction "
    "(pack objective) re-placing every movable pod against the "
    "cluster's fixed load.",
    buckets=_BUCKETS,
    registry=REGISTRY,
)
rebalance_packing_utilization = Gauge(
    "scheduler_rebalance_packing_utilization",
    "Dominant-resource packed utilization of the in-use nodes at "
    "the last rebalance pass (detector.py): max(cpu, mem) of "
    "used/allocatable over schedulable nodes hosting pods.",
    registry=REGISTRY,
)
rebalance_stranded_fraction = Gauge(
    "scheduler_rebalance_stranded_fraction",
    "Fraction of total free capacity stranded on partly-used nodes "
    "(free slivers between resident pods) at the last rebalance "
    "pass.",
    registry=REGISTRY,
)
rebalance_priority_inversions = Gauge(
    "scheduler_rebalance_priority_inversions",
    "Pending pods more important than the least important bound pod "
    "at the last fragmented rebalance pass — re-packing could seat "
    "them (advisory: the planner itself only consolidates).",
    registry=REGISTRY,
)

# -- cluster simulator (kubernetes_tpu/sim) --

sim_events_total = Counter(
    "scheduler_sim_events_total",
    "Cluster-churn events the simulator applied, by operation "
    "(create_pod|delete_pod|create_node|delete_node|flap_label|"
    "alloc_grow|alloc_shrink|external_bind).",
    ["op"],
    registry=REGISTRY,
)
sim_faults_injected_total = Counter(
    "scheduler_sim_faults_injected_total",
    "Faults the simulator injected at real boundaries, by fault kind "
    "(bind_conflict|watch_delay|watch_duplicate|extender_timeout|"
    "extender_5xx|permit_stall|solver_fault|poison_pod|crash|"
    "hub_partition|lease_fence).",
    ["fault"],
    registry=REGISTRY,
)
sim_invariant_violations_total = Counter(
    "scheduler_sim_invariant_violations_total",
    "Invariant violations the simulator's checkers flagged, by "
    "invariant (double_bind|capacity|lost_pod|progress|monotonic|"
    "constraint|journal|global_overcommit|resilience|recovery|"
    "fencing|rebalance|tuning|no_partial_gang_ever_bound|telemetry).",
    ["invariant"],
    registry=REGISTRY,
)
sim_cycles_total = Counter(
    "scheduler_sim_cycles_total",
    "Simulator churn cycles driven to completion.",
    registry=REGISTRY,
)

extender_batch_size = Histogram(
    "scheduler_tpu_extender_batch_size",
    "Webhook requests coalesced per device evaluation (micro-batching).",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
    registry=REGISTRY,
)
extender_request_seconds = Histogram(
    "scheduler_tpu_extender_request_seconds",
    "Wall time of one micro-batched extender evaluation.",
    buckets=_BUCKETS,
    registry=REGISTRY,
)


def render() -> bytes:
    """Prometheus exposition text for the /metrics endpoint."""
    return generate_latest(REGISTRY)
