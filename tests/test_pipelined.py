"""Double-buffered scheduling loop (Scheduler.run_pipelined, VERDICT r4 #1).

The pipelined loop overlaps batch k+1's tensorize/dispatch with batch k's
device→host read. These tests pin its three safety obligations:

1. observational equivalence — with a deterministic tie-break, pipelined
   bindings are identical to the synchronous loop's;
2. the conflict fence — a capacity/mask-affecting event landing between a
   solve's dispatch and its apply DISCARDS the solve (two-in-flight
   fencing): the pods retry immediately without backoff, the polluted
   device session re-uploads from host truth, and the re-solve respects
   the post-event cluster;
3. the deferred heal — dirty snapshot columns are not healed over an
   in-flight solve's carried placements; host truth only ever understates
   device usage under the fence, so deferral is conservative.

Reference: schedule_one.go#scheduleOne's bind-goroutine overlap [U] — the
same decoupling idea extended to the device boundary.
"""

import time

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig, SessionDrainRequired
from kubernetes_tpu.state.cluster import ClusterState

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def build(n_nodes, cpu="8", batch=64, group=16, n_pods=0, pod_cpu="500m", clock=None):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i:03}")
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": "110"})
            .label(HOST, f"n{i:03}")
            .obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            solver=ExactSolverConfig(tie_break="first", group_size=group),
        ),
        clock=clock,
    )
    for i in range(n_pods):
        cs.create_pod(
            MakePod().name(f"p{i:04}").req({"cpu": pod_cpu, "memory": "1Gi"}).obj()
        )
    return cs, sched


def bindings(cs):
    return sorted((p.name, p.node_name) for p in cs.list_pods())


def test_pipelined_matches_sync_bindings():
    cs1, s1 = build(50, n_pods=300)
    s1.run_until_settled()
    cs2, s2 = build(50, n_pods=300)
    results = s2.run_pipelined()
    assert bindings(cs1) == bindings(cs2)
    assert sum(len(r.scheduled) for r in results) == 300
    # multiple batches actually overlapped (300 pods / batch 64 = 5 cycles)
    assert len(results) >= 5


def test_pipelined_overfill_marks_unschedulable():
    # 4 nodes x 8 cpu / 500m = 64 slots for 100 pods
    cs, s = build(4, n_pods=100)
    results = s.run_pipelined()
    assert sum(len(r.scheduled) for r in results) == 64
    assert sum(len(r.unschedulable) for r in results) == 36
    # capacity respected on every node
    per_node = {}
    for p in cs.list_pods():
        if p.node_name:
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
    assert all(v <= 16 for v in per_node.values())


def _manual_flight(s, n_pods):
    """Pop + prep + dispatch one deferred batch, the way run_pipelined
    does, returning the in-flight solve."""
    t0 = time.perf_counter()
    with s.cluster.lock:
        infos = s.queue.pop_batch(s.config.batch_size)
        base = s.queue.scheduling_cycle - len(infos)
        for i in infos:
            s._in_flight[i.key] = i
    assert len(infos) == n_pods
    assert s._plain_batch([i.pod for i in infos])
    prep = s._tensorize_group(
        next(iter(s.solvers)), infos, list(range(len(infos))), base, t0
    )
    return s._dispatch_group(prep, defer=True, allow_heal=True)


def test_fence_discards_stale_solve_and_resolves_correctly():
    # one node, 8 cpu: 10 pods of 1 cpu -> 8 would fit pre-shrink
    cs, s = build(1, n_pods=10, pod_cpu="1")
    before = metrics.solves_discarded_total._value.get()
    flight = _manual_flight(s, 10)
    # conflicting event between dispatch and apply: allocatable shrinks
    node = cs.get_node("n000")
    shrunk = (
        MakeNode()
        .name("n000")
        .capacity({"cpu": "3", "memory": "32Gi", "pods": "110"})
        .label(HOST, "n000")
        .obj()
    )
    shrunk.resource_version = node.resource_version
    cs.update_node(shrunk)
    res = s._apply_flight(flight)
    # discarded: nothing applied, pods requeued without backoff or charge
    assert not res.scheduled and not res.unschedulable
    assert metrics.solves_discarded_total._value.get() == before + 1
    assert s._session_stale
    assert len(s.queue) == 10
    assert all(i.attempts == 0 for i in s.queue._info.values())
    # the retry (sync path resets the stale session) respects the shrink
    s.run_until_settled()
    placed = [p for p in cs.list_pods() if p.node_name]
    assert len(placed) == 3  # 3 cpu / 1 cpu each
    assert not s._session_stale


def test_fence_ignores_irrelevant_events():
    # a pure status-heartbeat node update must NOT discard the solve
    cs, s = build(2, n_pods=4)
    flight = _manual_flight(s, 4)
    node = cs.get_node("n000")
    same = (
        MakeNode()
        .name("n000")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
        .label(HOST, "n000")
        .obj()
    )
    same.resource_version = node.resource_version
    cs.update_node(same)  # no allocatable/label/taint/unschedulable change
    res = s._apply_flight(flight)
    assert len(res.scheduled) == 4
    assert not s._session_stale


def test_pipelined_external_delete_is_conservative_then_heals():
    """An assigned-pod DELETE mid-pipeline frees capacity. The deferred
    heal means in-flight solves do not see the freed space (conservative)
    but later batches do."""
    cs, s = build(1, cpu="4", batch=2, n_pods=0, pod_cpu="1")
    # preload the node to 3/4 cpu with bound pods
    for i in range(3):
        cs.create_pod(MakePod().name(f"old{i}").req({"cpu": "1"}).obj())
        cs.bind("default", f"old{i}", "n000")
    # first batch fills the node; a delete then frees one slot; the next
    # batches pick it up after the heal
    for i in range(4):
        cs.create_pod(MakePod().name(f"new{i}").req({"cpu": "1"}).obj())
    flight = _manual_flight(s, 2)
    cs.delete_pod("default", "old0")  # frees 1 cpu; does NOT bump fence
    res = s._apply_flight(flight)
    # solve ran against the pre-delete snapshot: 1 slot free -> 1 of 2
    assert len(res.scheduled) == 1 and len(res.unschedulable) == 1
    # drain the rest synchronously: the heal lands, freed slot is used
    s.run_until_settled()
    placed = sorted(
        p.name for p in cs.list_pods() if p.node_name and p.name.startswith("new")
    )
    assert len(placed) == 2  # 4 cpu - 2 remaining old = 2 slots


def test_session_drain_required_on_shape_change():
    import numpy as np

    from kubernetes_tpu.solver.exact import _DeviceSession
    from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab, pad_to

    def nb(n):
        vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
        npad = pad_to(n)
        live = np.arange(npad) < n
        return NodeBatch(
            vocab=vocab,
            names=[f"n{i}" for i in range(n)],
            num_nodes=n,
            padded=npad,
            allocatable=np.zeros((3, npad), np.int64),
            used=np.zeros((3, npad), np.int64),
            nonzero_used=np.zeros((2, npad), np.int64),
            pod_count=np.zeros(npad, np.int32),
            max_pods=np.where(live, 110, 0).astype(np.int32),
            valid=live,
            schedulable=live.copy(),
        )

    sess = _DeviceSession()
    small = nb(4)
    sess.sync(small, np.zeros(small.padded, np.int64))
    big = nb(small.padded + 1)  # crosses the padding bucket
    try:
        sess.sync(big, np.zeros(big.padded, np.int64), allow_heal=False)
        raise AssertionError("expected SessionDrainRequired")
    except SessionDrainRequired:
        pass
    # with healing allowed the same sync re-uploads cleanly
    sess.sync(big, np.zeros(big.padded, np.int64), allow_heal=True)
    assert sess.padded == big.padded


def test_deferred_heal_skips_and_later_heals():
    import numpy as np

    from kubernetes_tpu.solver.exact import _DeviceSession
    from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab, pad_to

    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    n = 4
    npad = pad_to(n)
    live = np.arange(npad) < n

    def nb(used0):
        used = np.zeros((3, npad), np.int64)
        used[0, 0] = used0
        return NodeBatch(
            vocab=vocab,
            names=[f"n{i}" for i in range(n)],
            num_nodes=n,
            padded=npad,
            allocatable=np.full((3, npad), 100, np.int64),
            used=used,
            nonzero_used=used[:2].copy(),
            pod_count=np.zeros(npad, np.int32),
            max_pods=np.where(live, 110, 0).astype(np.int32),
            valid=live,
            schedulable=live.copy(),
        )

    sess = _DeviceSession()
    vers = np.zeros(npad, np.int64)
    sess.sync(nb(0), vers)
    assert int(np.asarray(sess.persist["used"])[0, 0]) == 0
    vers2 = vers.copy()
    vers2[0] = 1  # column 0 dirtied
    sess.sync(nb(7), vers2, allow_heal=False)
    # deferred: device value unchanged, version not consumed
    assert int(np.asarray(sess.persist["used"])[0, 0]) == 0
    assert int(sess.seen_versions[0]) == 0
    sess.sync(nb(7), vers2, allow_heal=True)
    assert int(np.asarray(sess.persist["used"])[0, 0]) == 7
    assert int(sess.seen_versions[0]) == 1


def test_pipelined_nonplain_batch_matches_sync():
    """Spread-constrained pods take the occupancy-carrying pipelined
    mode (drain-then-chain — see test_pipelined_shapes.py for the
    no-drain regression); the result must still match the pure-sync
    loop."""

    def mk():
        cs = ClusterState()
        for i in range(6):
            cs.create_node(
                MakeNode()
                .name(f"n{i}")
                .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
                .label(ZONE, f"z{i % 3}")
                .label(HOST, f"n{i}")
                .obj()
            )
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first", group_size=8),
            ),
        )
        for i in range(30):
            cs.create_pod(
                MakePod()
                .name(f"s{i:03}")
                .label("app", "w")
                .req({"cpu": "100m"})
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "w"})
                .obj()
            )
        return cs, s

    cs1, s1 = mk()
    s1.run_until_settled()
    cs2, s2 = mk()
    s2.run_pipelined()
    assert bindings(cs1) == bindings(cs2)
    assert all(p.node_name for p in cs2.list_pods())


def test_pipelined_mixed_plain_and_nonplain():
    """Plain and constrained pods interleaved: pipelined cycles drain
    before a non-plain batch tensorizes, so cross-batch occupancy state
    (here hostname anti-affinity) stays exact."""
    cs = ClusterState()
    for i in range(8):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label(HOST, f"n{i}")
            .obj()
        )
    s = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=8,
            solver=ExactSolverConfig(tie_break="first", group_size=4),
        ),
    )
    for i in range(16):
        cs.create_pod(
            MakePod().name(f"plain{i:02}").req({"cpu": "100m"}).obj()
        )
    for i in range(8):
        cs.create_pod(
            MakePod()
            .name(f"anti{i}")
            .label("app", "a")
            .req({"cpu": "100m"})
            .pod_anti_affinity(HOST, {"app": "a"})
            .obj()
        )
    s.run_pipelined()
    placed = [p for p in cs.list_pods() if p.node_name]
    assert len(placed) == 24
    anti_nodes = [p.node_name for p in placed if p.name.startswith("anti")]
    assert len(set(anti_nodes)) == 8  # one per node


def test_fence_recheck_under_lock():
    """The fence is re-validated inside _apply_group's locked region: an
    event landing after _apply_flight's unlocked pre-check (e.g. during
    the device read) still discards the solve."""
    cs, s = build(2, n_pods=4)
    flight = _manual_flight(s, 4)
    # simulate the conflict landing inside the check-to-lock window by
    # calling _apply_group directly with the recorded fence after a bump
    s._conflict_seq += 1
    from kubernetes_tpu.scheduler import BatchResult

    res = BatchResult()
    assert s._apply_group(flight, res, [], fence=flight.prep.fence) is False
    assert not res.scheduled
    # and the full _apply_flight wrapper routes that into a discard
    assert len(s.queue) == 0  # pods still held in _in_flight
    r2 = s._apply_flight(flight)
    assert not r2.scheduled and len(s.queue) == 4
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())


def test_discard_skips_externally_bound_and_deleted_pods():
    cs, s = build(2, n_pods=4)
    flight = _manual_flight(s, 4)
    # mid-flight: p0000 is bound by another actor (bumps the fence),
    # p0001 is deleted
    cs.bind("default", "p0000", "n001")
    cs.delete_pod("default", "p0001")
    res = s._apply_flight(flight)
    assert not res.scheduled  # discarded
    # only the two still-pending pods requeue; no ghost entries
    assert sorted(s.queue._info) == ["default/p0002", "default/p0003"]
    s.run_until_settled()
    placed = {p.name: p.node_name for p in cs.list_pods() if p.node_name}
    assert set(placed) == {"p0000", "p0002", "p0003"}


def test_discard_storm_backstop_makes_progress():
    """Livelock backstop (ADVICE r5 #2): a capacity-bumping watch event
    landing in EVERY dispatch→apply window discards every fenced solve;
    after _PIPELINE_FALLBACK_AFTER consecutive discards the loop must
    fall back to one synchronous (fence-free) cycle and land the batch
    anyway."""
    cs, s = build(2, batch=4, n_pods=12)
    fallbacks_before = metrics.pipeline_fallback_total._value.get()
    cpu = [16]
    real_dispatch = s._dispatch_group

    def churny_dispatch(prep, defer, allow_heal=True):
        flight = real_dispatch(prep, defer, allow_heal)
        # a node-capacity grow event lands while the solve is in flight:
        # _node_change_could_help -> fence bump -> the apply discards
        cpu[0] += 1
        node = cs.get_node("n000")
        grown = (
            MakeNode()
            .name("n000")
            .capacity({"cpu": str(cpu[0]), "memory": "32Gi", "pods": "110"})
            .label(HOST, "n000")
            .obj()
        )
        grown.resource_version = node.resource_version
        cs.update_node(grown)
        return flight

    s._dispatch_group = churny_dispatch
    results = s.run_pipelined(max_batches=200)
    assert sum(len(r.scheduled) for r in results) == 12
    assert all(p.node_name for p in cs.list_pods())
    assert metrics.pipeline_fallback_total._value.get() > fallbacks_before
    # the storm really was a storm: fenced solves did get discarded
    assert s._discard_streak == 0 or len(s.queue) == 0


def test_apply_exception_marks_session_stale_and_heals():
    """ADVICE r5 #3, upgraded by the resilience layer: a deferred
    assignment read dying (device/session loss after dispatch) no
    longer crashes the loop — the flight discards, the device session
    is marked stale (its carried state counted placements that never
    bound), the failure is charged to the solve breaker, and the pods
    requeue for an immediate retry through the resilient path."""
    from kubernetes_tpu import metrics
    from kubernetes_tpu.solver.exact import DeferredAssignments
    from kubernetes_tpu.utils.clock import FakeClock

    clock = FakeClock()
    cs, s = build(2, n_pods=6, clock=clock)
    flight = _manual_flight(s, 6)

    class Boom(DeferredAssignments):
        def __init__(self):  # no device handle; the read itself dies
            pass

        def get(self):
            raise RuntimeError("device read failed")

    failures_before = metrics.batch_failure_total.labels(
        "read"
    )._value.get()
    flight.handle = Boom()
    res = s._apply_flight(flight)  # no raise: the resilience layer owns it
    assert not res.scheduled
    assert s._session_stale  # carry no longer trusted
    assert len(s.queue) == 6  # every pod requeued, none stranded
    assert not s._in_flight  # bookkeeping torn down
    # the failure was journaled/counted, not silently swallowed
    assert (
        metrics.batch_failure_total.labels("read")._value.get()
        == failures_before + 1
    )
    # and the retry routes through the synchronous resilient path
    assert s.resilience.should_sync()
    # the drain heals: the stale session re-uploads from host truth
    # and everything fits (the pods were requeued with no backoff)
    s.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    assert not s._session_stale
    assert not s.resilience.should_sync()  # sync retry cleared the flag


def test_requeue_popped_uncharges_attempt():
    cs, s = build(1, n_pods=1)
    with s.cluster.lock:
        infos = s.queue.pop_batch(8)
    assert infos[0].attempts == 1
    s.queue.requeue_popped(infos[0])
    assert len(s.queue) == 1
    with s.cluster.lock:
        again = s.queue.pop_batch(8)
    assert again[0].attempts == 1  # not 2: the discarded pop was free
