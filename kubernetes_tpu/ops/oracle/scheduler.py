"""Sequential oracle scheduler: the reference's scheduleOne loop in plain
Python, used to (a) produce ground-truth assignments and (b) validate solver
output under any tie-break policy.

Mirrors pkg/scheduler/schedule_one.go#schedulePod with the default
NodeResourcesFit(LeastAllocated) + BalancedAllocation scoring profile: filter
all nodes, score feasible ones, pick max. The reference picks uniformly among
max-score ties (selectHost); parity therefore means "the solver's pick is a
member of the oracle's tie set at that step, given identical history"
(SURVEY.md §8.8). validate_assignments replays the solver's own choices so
downstream state stays identical while each choice is checked against the
tie set.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from ...api.objects import Node, Pod
from .noderesources import (
    NodeState,
    balanced_allocation_score,
    fit_filter,
    least_allocated_score,
)


def make_node_states(
    nodes: Sequence[Node], pods_by_node: dict[str, list[Pod]] | None = None
) -> list[NodeState]:
    out = []
    for n in nodes:
        st = NodeState(
            name=n.name,
            allocatable=dict(n.allocatable),
            max_pods=n.allowed_pod_number,
            schedulable=not n.unschedulable,
        )
        for p in (pods_by_node or {}).get(n.name, []):
            st.add_pod(p)
        out.append(st)
    return out


def score_one(pod: Pod, node: NodeState) -> int:
    return least_allocated_score(pod, node) + balanced_allocation_score(pod, node)


def feasible_and_ties(
    pod: Pod, nodes: Sequence[NodeState]
) -> tuple[list[int], list[int]]:
    """Returns (feasible node indices, tie-set = argmax-score indices)."""
    feasible = [
        i
        for i, st in enumerate(nodes)
        if st.schedulable and not fit_filter(pod, st)
    ]
    if not feasible:
        return [], []
    scores = {i: score_one(pod, nodes[i]) for i in feasible}
    best = max(scores.values())
    ties = [i for i in feasible if scores[i] == best]
    return feasible, ties


@dataclass
class OracleResult:
    assignments: list[int]  # chosen node index per pod, -1 = unschedulable
    tie_sets: list[list[int]]


def schedule(
    pods: Sequence[Pod], nodes: list[NodeState], tie_break: str = "first"
) -> OracleResult:
    """Run the full sequential loop, choosing the first (lowest-index) tie.
    Note: with tie_break='first' this is deterministic ground truth for the
    solver's 'first' mode."""
    assert tie_break == "first"
    assignments: list[int] = []
    tie_sets: list[list[int]] = []
    for pod in pods:
        _, ties = feasible_and_ties(pod, nodes)
        if not ties:
            assignments.append(-1)
            tie_sets.append([])
            continue
        pick = ties[0]
        nodes[pick].add_pod(pod)
        assignments.append(pick)
        tie_sets.append(ties)
    return OracleResult(assignments, tie_sets)


def validate_assignments(
    pods: Sequence[Pod], nodes: list[NodeState], assignments: Sequence[int]
) -> list[str]:
    """Replay the solver's choices, checking each against the oracle tie set.
    Returns a list of violation messages (empty = parity holds)."""
    errors: list[str] = []
    for step, (pod, pick) in enumerate(zip(pods, assignments)):
        _, ties = feasible_and_ties(pod, nodes)
        if pick == -1:
            if ties:
                errors.append(
                    f"step {step} pod {pod.key}: solver says unschedulable but "
                    f"oracle tie set is {ties}"
                )
            continue
        if pick not in ties:
            errors.append(
                f"step {step} pod {pod.key}: pick {pick} not in oracle tie set "
                f"{ties[:10]}{'...' if len(ties) > 10 else ''}"
            )
            # follow the solver anyway to localize subsequent divergence
        nodes[pick].add_pod(pod)
    return errors
