"""Label selectors: parse + host-side evaluation.

Reference semantics:
- staging/src/k8s.io/apimachinery/pkg/labels/selector.go#Requirement.Matches
- staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go#LabelSelector
  (matchLabels AND matchExpressions, all requirements ANDed)
- NodeSelectorRequirement operators (In/NotIn/Exists/DoesNotExist/Gt/Lt) from
  staging/src/k8s.io/api/core/v1/types.go#NodeSelectorOperator, evaluated in
  k8s.io/component-helpers/scheduling/corev1/nodeaffinity/nodeaffinity.go.

Matching rules (same as reference):
- In:            key present and value in values
- NotIn:         key absent OR value not in values
- Exists:        key present
- DoesNotExist:  key absent
- Gt / Lt:       key present, label value parses as integer, int(label) >/< int(values[0])

An empty LabelSelector ({}) matches everything; a nil selector matches nothing
(callers encode that by passing None).

These evaluate host-side; the tensorizer (kubernetes_tpu/tensorize) compiles
the same requirements into bitset index programs for on-device evaluation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

# metav1.LabelSelector only admits these (apimachinery#LabelSelectorAsSelector
# returns an error for anything else); NodeSelectorRequirement additionally
# admits Gt/Lt (core/v1#NodeSelectorOperator).
_LABEL_SELECTOR_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST}
_NODE_SELECTOR_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


@dataclass(frozen=True)
class Requirement:
    """One selector requirement: key <op> values."""

    key: str
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        if self.operator == IN:
            return present and labels[self.key] in self.values
        if self.operator == NOT_IN:
            return (not present) or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return present
        if self.operator == DOES_NOT_EXIST:
            return not present
        if self.operator in (GT, LT):
            if not present or len(self.values) != 1:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown selector operator {self.operator!r}")


@dataclass(frozen=True)
class Selector:
    """AND of requirements. ``Selector(())`` matches everything."""

    requirements: tuple[Requirement, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.requirements


def selector_from_label_selector(obj: Mapping | None) -> Selector | None:
    """Build a Selector from a metav1.LabelSelector-shaped dict.

    Returns None for a nil selector (matches nothing), Selector(()) for the
    empty selector (matches everything) — mirroring
    apimachinery#LabelSelectorAsSelector.
    """
    if obj is None:
        return None
    reqs: list[Requirement] = []
    for k, v in sorted((obj.get("matchLabels") or {}).items()):
        reqs.append(Requirement(k, IN, (v,)))
    for expr in obj.get("matchExpressions") or ():
        op = expr.get("operator")
        if op not in _LABEL_SELECTOR_OPS:
            raise ValueError(f"invalid matchExpressions operator {op!r}")
        reqs.append(
            Requirement(expr["key"], op, tuple(expr.get("values") or ()))
        )
    return Selector(tuple(reqs))


def selector_from_node_selector_requirements(exprs) -> Selector:
    """Build a Selector from NodeSelectorRequirement dicts (Gt/Lt allowed)."""
    reqs: list[Requirement] = []
    for expr in exprs or ():
        op = expr.get("operator")
        if op not in _NODE_SELECTOR_OPS:
            raise ValueError(f"invalid nodeSelector operator {op!r}")
        reqs.append(Requirement(expr["key"], op, tuple(expr.get("values") or ())))
    return Selector(tuple(reqs))


def requirements_from_match_labels(match_labels: Mapping[str, str]) -> tuple[Requirement, ...]:
    return tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))


def label_selector_to_dict(sel: Selector | None) -> dict | None:
    """Inverse of selector_from_label_selector, for wire round-trips."""
    if sel is None:
        return None
    exprs = []
    for r in sel.requirements:
        exprs.append({"key": r.key, "operator": r.operator, "values": list(r.values)})
    return {"matchExpressions": exprs} if exprs else {}


def matches_any(selectors: Iterable[Selector], labels: Mapping[str, str]) -> bool:
    return any(s.matches(labels) for s in selectors)
