"""Outbound extender client (VERDICT r3 #5): the scheduler consults
configured extenders[] during the solve, golden-tested against THIS
repo's own extender server — the self-hosting loop that closes both
halves of the boundary (pkg/scheduler/extender.go client semantics vs
server/extender.py wire shapes)."""

import asyncio
import threading

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.config.types import Extender
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.server.extender import ExtenderCore, make_app
from kubernetes_tpu.server.extender_client import (
    ExtenderError,
    HTTPExtenderClient,
)
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState


def _serve(app):
    """Run an aiohttp app on a real socket in a daemon thread; returns
    (base_url, stop). The scheduler's client is synchronous urllib, so
    TestClient won't do."""
    from aiohttp import web

    loop = asyncio.new_event_loop()
    started = threading.Event()
    holder = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    assert started.wait(10)

    def stop():
        loop.call_soon_threadsafe(loop.stop)

    return f"http://127.0.0.1:{holder['port']}", stop


def mk_node(name):
    return (
        MakeNode()
        .name(name)
        .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
        .obj()
    )


def _sched(cs, extenders):
    return Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first"),
            extenders=tuple(extenders),
        ),
    )


def test_outbound_filter_changes_bindings():
    """A live extender whose watch-fed view holds ONLY node-1 restricts
    the solve: unknown names come back as failedNodes (nodeCacheCapable)
    and the pod lands where the extender allows, not where the default
    tie-break would."""
    cs = ClusterState()
    for i in range(4):
        cs.create_node(mk_node(f"node-{i}"))
    ext_view = ClusterState()
    ext_view.create_node(mk_node("node-1"))
    url, stop = _serve(make_app(ExtenderCore(ext_view, node_cache_capable=True)))
    try:
        sched = _sched(
            cs,
            [Extender(url_prefix=url, filter_verb="filter",
                      node_cache_capable=True)],
        )
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = sched.schedule_batch()
        assert dict(r.scheduled) == {"default/p": "node-1"}
    finally:
        stop()


def test_outbound_prioritize_steers_bindings():
    """Extender prioritize scores rescale by weight * MaxNodeScore /
    MaxExtenderPriority and accumulate into the device tables: a
    high-weight extender that only knows node-2 out-pulls the in-tree
    tie-break."""
    cs = ClusterState()
    for i in range(4):
        cs.create_node(mk_node(f"node-{i}"))
    ext_view = ClusterState()
    ext_view.create_node(mk_node("node-2"))
    url, stop = _serve(make_app(ExtenderCore(ext_view, node_cache_capable=True)))
    try:
        sched = _sched(
            cs,
            [Extender(url_prefix=url, prioritize_verb="prioritize",
                      node_cache_capable=True, weight=5)],
        )
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = sched.schedule_batch()
        assert dict(r.scheduled) == {"default/p": "node-2"}
    finally:
        stop()


def test_outbound_bind_delegation():
    """A bind-verb extender owns the binding subresource call: the
    scheduler delegates and the bind lands through the server (same
    state service = the watch confirms it, like the reference's
    apiserver round trip)."""
    cs = ClusterState()
    for i in range(2):
        cs.create_node(mk_node(f"node-{i}"))
    url, stop = _serve(make_app(ExtenderCore(cs)))
    try:
        sched = _sched(
            cs, [Extender(url_prefix=url, bind_verb="bind")]
        )
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = sched.schedule_batch()
        assert len(r.scheduled) == 1
        assert cs.get_pod("default", "p").node_name == "node-0"
    finally:
        stop()


def test_ignorable_extender_outage_is_skipped():
    cs = ClusterState()
    for i in range(2):
        cs.create_node(mk_node(f"node-{i}"))
    dead = Extender(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
        ignorable=True,
    )
    sched = _sched(cs, [dead])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 1  # outage ignored, in-tree verdicts hold


def test_non_ignorable_extender_outage_aborts_without_stranding():
    """The outage surfaces as an error, but the popped pod must not be
    lost: it requeues with backoff and schedules once the extender
    recovers (review-caught: the raise used to strand the whole batch)."""
    cs = ClusterState()
    cs.create_node(mk_node("node-0"))
    dead = Extender(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
    )
    sched = _sched(cs, [dead])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    with pytest.raises(ExtenderError):
        sched.schedule_batch()
    assert len(sched.queue) == 1, "popped pod requeued, not stranded"
    # 'recovery': swap the client set for a healthy (empty) one
    sched.extender_clients = ()
    sched.queue.flush_unschedulable_leftover()
    sched.queue.move_all_to_active_or_backoff("ExtenderRecovered")
    sched.clock = sched.clock  # backoff is wall-clock; force-flush below
    import time as _t

    _t.sleep(1.1)  # initial backoff 1s
    r = sched.schedule_batch()
    assert len(r.scheduled) == 1


def test_managed_resources_gate_is_interested():
    gpu_only = Extender(
        url_prefix="http://x", filter_verb="filter",
        managed_resources=[{"name": "example.com/gpu"}],
    )
    cl = HTTPExtenderClient(gpu_only)
    plain = MakePod().name("plain").req({"cpu": "1"}).obj()
    gpu = MakePod().name("gpu").req(
        {"cpu": "1", "example.com/gpu": "2"}
    ).obj()
    assert not cl.is_interested(plain)
    assert cl.is_interested(gpu)
