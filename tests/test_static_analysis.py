"""Gate + fixture tests for kubernetes_tpu.analysis.

The gate runs the analyzer in-process over the whole package and fails
on ANY unsuppressed finding — the tier-1 equivalent of scripts/lint.py.
The fixture tests prove each rule actually fires on a known-bad snippet
(a rule that never fires gates nothing), including LOCK001 catching the
pre-fix ``_apply_flight`` exception-path pattern it was built for.
"""

import textwrap

from kubernetes_tpu import analysis
from kubernetes_tpu.analysis import AnalysisContext, analyze_source
from kubernetes_tpu.analysis.passes import (
    DtypeDisciplinePass,
    HostSyncPass,
    LockDisciplinePass,
    MetricNamePass,
    TracedBranchPass,
)


def findings_for(source, passes, ctx=None, filename="snippet.py"):
    return analyze_source(
        textwrap.dedent(source), filename=filename, ctx=ctx, passes=passes
    )


def active(findings, rule=None):
    return [
        f
        for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# -- the gate ---------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings():
    """python -m kubernetes_tpu.analysis kubernetes_tpu/ must exit 0."""
    findings = analysis.run_paths()
    bad = active(findings)
    assert not bad, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in bad
    )


def test_every_suppression_carries_a_reason():
    findings = analysis.run_paths()
    assert not [f for f in findings if f.rule == "KTPU000"]
    for f in findings:
        if f.suppressed:
            assert f.suppress_reason.strip()


# -- TPU001 host-sync-in-hot-path ------------------------------------------

_JIT_SYNC = """
    import jax
    import numpy as np

    def leaf(x):
        return np.asarray(x).sum()

    @jax.jit
    def solve(x):
        return leaf(x) + 1
"""


def test_tpu001_fires_on_np_asarray_reachable_from_jit():
    fs = findings_for(_JIT_SYNC, [HostSyncPass])
    assert active(fs, "TPU001"), "np.asarray reachable from jax.jit missed"
    assert any("leaf" in f.message for f in fs)


def test_tpu001_fires_on_coercion_and_block_until_ready():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(x):
            y = x.block_until_ready()
            return int(y)
        """,
        [HostSyncPass],
    )
    msgs = [f.message for f in active(fs, "TPU001")]
    assert any("block_until_ready" in m for m in msgs)
    assert any("int() coercion" in m for m in msgs)


def test_tpu001_fires_in_registered_hot_function():
    fs = findings_for(
        """
        # the apply path: ktpu: hot
        def apply(batch):
            return batch.assignments.tolist()
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001")


def test_tpu001_hot_scope_skips_plain_host_coercions():
    """int()/float() on host values is legitimate outside traced code."""
    fs = findings_for(
        """
        # ktpu: hot
        def apply(batch):
            return int(batch.count) + float(batch.score)
        """,
        [HostSyncPass],
    )
    assert not active(fs, "TPU001")


def test_tpu001_whitelist_exempts_sanctioned_read_point():
    src = """
        import numpy as np

        class DeferredAssignments:
            # ktpu: hot
            def get(self):
                return np.asarray(self._dev)
    """
    hit = findings_for(src, [HostSyncPass], filename="exact.py")
    assert active(hit, "TPU001"), "unwhitelisted read must be flagged"
    ctx = AnalysisContext(
        sanctioned_sync=frozenset({("exact.py", "DeferredAssignments.get")})
    )
    ok = findings_for(src, [HostSyncPass], ctx=ctx, filename="exact.py")
    assert not active(ok, "TPU001")


def test_tpu001_jit_assignment_form_is_a_root():
    """g = jax.jit(f) roots f even without a decorator."""
    fs = findings_for(
        """
        import jax
        import numpy as np

        def _scan(x):
            return np.asarray(x)

        _scan_jit = jax.jit(_scan)
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001")


def test_tpu001_bare_name_resolves_to_module_function_not_sibling_method():
    """A bare name inside a method is the module-level function (a
    sibling method needs `self.`); scope must follow the right callee."""
    fs = findings_for(
        """
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        class S:
            def helper(self, x):
                return x  # clean sibling that must NOT shadow the call

            @jax.jit
            def solve(self, x):
                return helper(x)
        """,
        [HostSyncPass],
    )
    hits = active(fs, "TPU001")
    assert hits and all("'helper'" in f.message for f in hits)


def test_tpu001_sees_functions_defined_in_except_handlers():
    fs = findings_for(
        """
        import jax
        import numpy as np

        @jax.jit
        def solve(x):
            try:
                return x
            except Exception:
                def rescue(v):
                    return np.asarray(v)

                return rescue(x)
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001"), "def inside except handler escaped scope"


def test_cli_errors_on_nonexistent_path(tmp_path):
    """A typo'd path must not leave the gate silently green."""
    import pytest

    from kubernetes_tpu.analysis import run_paths
    from kubernetes_tpu.analysis.__main__ import main

    with pytest.raises(FileNotFoundError):
        run_paths([str(tmp_path / "no_such_dir")])
    assert main([str(tmp_path / "no_such_dir")]) == 2


def test_tpu001_suppression_with_reason_is_honored():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(shape):
            # ktpu: ignore[TPU001]: shape is a static argname
            return int(shape[0])
        """,
        [HostSyncPass],
    )
    assert not active(fs, "TPU001")
    assert any(f.suppressed for f in fs)


def test_reasonless_suppression_is_its_own_finding():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(shape):
            # ktpu: ignore[TPU001]
            return int(shape[0])
        """,
        [HostSyncPass],
    )
    assert active(fs, "KTPU000"), "reasonless ignore must be rejected"


# -- TPU002 traced-branch ---------------------------------------------------


def test_tpu002_fires_on_python_if_over_jnp():
    fs = findings_for(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            while jnp.sum(x) < 3:
                x = x + 1
            return -x
        """,
        [TracedBranchPass],
    )
    assert len(active(fs, "TPU002")) == 2


def test_tpu002_fires_in_hot_scope_as_implicit_sync():
    """if jnp.any(...) in HOST hot-path code syncs on every call."""
    fs = findings_for(
        """
        import jax.numpy as jnp

        # ktpu: hot
        def apply(rows):
            if jnp.any(rows < 0):
                return None
            return rows
        """,
        [TracedBranchPass],
    )
    hits = active(fs, "TPU002")
    assert len(hits) == 1
    assert "syncs per call" in hits[0].message


def test_tpu002_allows_static_python_branches():
    fs = findings_for(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":
                return x * 2
            return x
        """,
        [TracedBranchPass],
    )
    assert not active(fs, "TPU002")


# -- TPU003 dtype discipline ------------------------------------------------

_DTYPE_CTX = AnalysisContext(dtype_paths=("",))


def test_tpu003_fires_on_missing_dtype_and_float_literal():
    fs = findings_for(
        """
        import jax.numpy as jnp

        def build(n):
            a = jnp.zeros(n)
            b = jnp.full(n, 0.5)
            c = jnp.array([True])
            return a, b, c
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    hits = active(fs, "TPU003")
    assert len(hits) == 3
    assert any("float literal" in f.message for f in hits)


def test_tpu003_accepts_keyword_and_positional_dtype():
    fs = findings_for(
        """
        import jax.numpy as jnp

        def build(n, x):
            a = jnp.zeros(n, jnp.int32)
            b = jnp.full(n, 0, jnp.int64)
            c = jnp.array([1], dtype=jnp.int32)
            d = jnp.zeros_like(x)
            return a, b, c, d
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    assert not active(fs, "TPU003")


def test_tpu003_fires_on_narrow_flattened_index():
    # the 512k x 102k audit (ISSUE 12): a pod·node flattened index
    # narrowed to int32 in the same expression wraps silently at scale
    fs = findings_for(
        """
        import jax.numpy as jnp

        def flatten(pod_ids, node_ids, n):
            a = (pod_ids * n + node_ids).astype(jnp.int32)
            b = (pod_ids * n + node_ids).astype(dtype=jnp.int32)
            return a, b
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    hits = active(fs, "TPU003")
    assert len(hits) == 2  # positional AND keyword dtype forms
    assert all("flattened-index" in f.message for f in hits)


def test_tpu003_narrow_flatten_accepts_int64_and_float_scores():
    fs = findings_for(
        """
        import jax.numpy as jnp

        MAX_NODE_SCORE = 100

        def ok(pod_ids, node_ids, n, frac):
            wide = (pod_ids.astype(jnp.int64) * n + node_ids)
            narrow_named = wide.astype(jnp.int32)  # named, not inline
            score = ((1.0 - frac) * MAX_NODE_SCORE).astype(jnp.int32)
            ratio = (frac * MAX_NODE_SCORE / 2).astype(jnp.int32)
            return narrow_named, score, ratio
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    assert not active(fs, "TPU003")


def test_tpu003_scoped_to_configured_paths():
    fs = findings_for(
        "import jax.numpy as jnp\nx = jnp.zeros(3)\n",
        [DtypeDisciplinePass],
        ctx=AnalysisContext(dtype_paths=("kubernetes_tpu/ops/",)),
        filename="elsewhere.py",
    )
    assert not active(fs, "TPU003")


# -- LOCK001 lock discipline ------------------------------------------------

# Distilled from the PRE-FIX _apply_flight/_commit_all exception path:
# guarded in-flight bookkeeping and the session-stale flag touched on the
# failure path without the lock the happy path holds (ADVICE r5 #3).
_PREFIX_APPLY_FLIGHT = """
    class Scheduler:
        def __init__(self, cluster):
            self.cluster = cluster
            self._in_flight = {}  # ktpu: guarded-by(cluster.lock)
            self._session_stale = False  # ktpu: guarded-by(cluster.lock)

        def _apply_flight(self, flight):
            try:
                with self.cluster.lock:
                    self._in_flight.update(flight.infos)
            except Exception:
                # exception path: bookkeeping torn down WITHOUT the lock
                for info in flight.infos:
                    self._in_flight.pop(info.key, None)
                self._session_stale = True
                raise
"""


def test_lock001_catches_prefix_apply_flight_exception_path():
    fs = findings_for(_PREFIX_APPLY_FLIGHT, [LockDisciplinePass])
    hits = active(fs, "LOCK001")
    assert len(hits) == 2
    assert any("_in_flight" in f.message for f in hits)
    assert any("_session_stale" in f.message for f in hits)
    # the happy path (inside the with) is NOT flagged: both hits sit in
    # the except handler, after the locked update
    locked_line = next(
        i + 1
        for i, l in enumerate(_PREFIX_APPLY_FLIGHT.splitlines())
        if "update" in l
    )
    assert all(f.line > locked_line for f in hits)


def test_lock001_accepts_with_lock_and_holds_annotation():
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self._seq = 0  # ktpu: guarded-by(_lock)

            def bump(self):
                with self._lock:
                    self._seq += 1

            # watch callbacks fire under the lock: ktpu: holds(_lock)
            def on_event(self, ev):
                self._seq += 1
        """,
        [LockDisciplinePass],
    )
    assert not active(fs, "LOCK001")


def test_lock001_unannotated_attrs_are_free():
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self.counter = 0

            def bump(self):
                self.counter += 1
        """,
        [LockDisciplinePass],
    )
    assert not active(fs, "LOCK001")


def test_lock001_flags_real_scheduler_gap_when_annotations_stand():
    """The shipped Scheduler class passes ONLY because the exception
    paths now lock; stripping one lock re-fires the rule (guards the
    guard)."""
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self._in_flight = {}  # ktpu: guarded-by(cluster.lock)

            def _commit_all(self, infos):
                for info in infos:
                    self._in_flight.pop(info.key, None)
        """,
        [LockDisciplinePass],
    )
    assert active(fs, "LOCK001")


# -- MET001 metric names ----------------------------------------------------

_MET_CTX = AnalysisContext(
    metric_scan_paths=("",),
    metric_attrs={
        "solve_latency_seconds": "scheduler_tpu_solve_latency_seconds",
        "render": None,
    },
)


def test_met001_fires_on_unknown_attr_and_series_string():
    fs = findings_for(
        """
        from . import metrics

        def record():
            metrics.solve_latency_seconds.observe(1.0)
            metrics.solve_latency_sconds.observe(1.0)  # typo
            return "scheduler_tpu_solve_latency_secnds"  # typo
        """,
        [MetricNamePass],
        ctx=_MET_CTX,
    )
    hits = active(fs, "MET001")
    assert len(hits) == 2
    assert any("solve_latency_sconds" in f.message for f in hits)
    assert any("secnds" in f.message for f in hits)


def test_met001_shipped_registry_resolves_real_usage():
    """The real metrics module must expose every series the scheduler
    records — including the new pipeline fallback counter."""
    from kubernetes_tpu.analysis.passes.metricnames import (
        load_metric_registry,
    )

    attrs = load_metric_registry()
    assert attrs["pipeline_fallback_total"] == (
        "scheduler_pipeline_fallback_total"
    )
    assert attrs["solves_discarded_total"] == (
        "scheduler_tpu_solves_discarded_total"
    )
