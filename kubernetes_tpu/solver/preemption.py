"""Batched preemption dry-run (SURVEY.md §8.5).

The reference dry-runs SelectVictimsOnNode per candidate node inside a
16-way parallel-for (preemption.go#DryRunPreemption). Here ONE compiled
program evaluates every node at once:

- Phase A: remove ALL lower-priority pods per node (their aggregated
  requests arrive precomputed as ``lower_sum``), assume the incoming pod,
  check fit -> candidate mask over the whole node axis.
- Phase B: greedy reprieve as a lax.scan over the per-node victim-slot axis
  (PDB-violating candidates first, then non-violating, each in
  MoreImportantPod order — the ordering is precompiled host-side into the
  slot order, so the device loop is just "does it still fit if I re-add
  slot s", vectorized over nodes).
- Phase C: per-node victim statistics for pickOneNodeForPreemption
  (violations, max/sum victim priority, victim count, latest start among
  top-priority victims); the final lexicographic argmin runs host-side on
  [N] arrays.

Candidacy is gated on the pod's static per-node feasibility (taints,
affinity, nodeName, unschedulable) — preemption cannot resolve those, which
mirrors the reference skipping UnschedulableAndUnresolvable nodes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..api.objects import Node, Pod
from ..ops.oracle.preemption import (
    PodDisruptionBudget,
    classify_pdb_violations,
    sort_more_important,
)
from ..tensorize.schema import NodeBatch, bucket_pow2

SLOT_PAD = 8
NEG = -(1 << 30)


def _preempt_scan(
    alloc,  # [K, N]
    max_pods,  # [N]
    keep_used,  # [K, N] — usage by pods that stay (priority >= incoming)
    keep_cnt,  # [N]
    static_ok,  # [N] bool
    req,  # [K]
    cand_req,  # [S, K, N] — reprieve-ordered victim-candidate requests
    cand_active,  # [S, N] bool
    cand_viol,  # [S, N] bool
    cand_prio,  # [S, N] int32
    cand_start,  # [S, N] float32
):
    base_used = keep_used + req[:, None]
    fits_all = (
        jnp.all(base_used <= alloc, axis=0)
        & (keep_cnt + 1 <= max_pods)
        & static_ok
    )

    def step(carry, xs):
        used_cur, cnt_cur = carry
        c_req, c_active = xs
        try_used = used_cur + c_req
        ok = (
            jnp.all(try_used <= alloc, axis=0)
            & (cnt_cur + 1 <= max_pods)
            & c_active
        )
        used_cur = jnp.where(ok[None, :], try_used, used_cur)
        cnt_cur = cnt_cur + ok.astype(cnt_cur.dtype)
        victim = c_active & ~ok
        return (used_cur, cnt_cur), victim

    (_, _), victims = jax.lax.scan(
        step, (base_used, keep_cnt + 1), (cand_req, cand_active)
    )  # victims: [S, N]

    n_victims = jnp.sum(victims, axis=0).astype(jnp.int32)
    n_viol = jnp.sum(victims & cand_viol, axis=0).astype(jnp.int32)
    vic_prio = jnp.where(victims, cand_prio, NEG)
    max_prio = jnp.max(vic_prio, axis=0)
    sum_prio = jnp.sum(jnp.where(victims, cand_prio, 0), axis=0)
    top = victims & (cand_prio == max_prio[None, :])
    latest_top_start = jnp.max(
        jnp.where(top, cand_start, -jnp.inf), axis=0
    )
    return fits_all, victims, n_victims, n_viol, max_prio, sum_prio, latest_top_start


_preempt_scan_jit = jax.jit(_preempt_scan)


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[Pod]
    num_violating: int


class PreemptionEvaluator:
    """Host driver: builds the per-pod candidate tensors, runs the batched
    dry-run, applies pickOneNodeForPreemption.

    Two-phase design (SURVEY §8.5 + reference SelectVictimsOnNode):
    the batched device dry-run is a fit-only pre-screen + ranking over ALL
    nodes at once; when the pod's failure can involve beyond-fit filters
    (ports/spread/interpod), at least the top ``refine_k`` ranked candidates
    (and more until one yields victims) are re-evaluated with the
    full-filter scalar oracle
    (select_victims_on_node_full), which also computes the exact victim set
    under per-re-add filter re-runs. When no beyond-fit filter is in play,
    fit-only IS the full pipeline (static per-node feasibility is already
    gated), so the device result commits directly.
    """

    def __init__(self, refine_k: int = 100):
        # Floor mirrors the reference's candidate sampling
        # (preemption.go#GetOffsetAndNumCandidates: minCandidateNodesAbsolute
        # = 100): at least this many fit-ranked candidates get the exact
        # full-filter dry-run. If none of them yields victims, refinement
        # keeps walking the remaining ranked candidates until one does (the
        # fit-only ranking is a heuristic; a feasible candidate must never be
        # lost to the cutoff).
        self.refine_k = refine_k

    def _dry_run(
        self,
        pod: Pod,
        nodes: NodeBatch,
        placed_by_slot: dict[int, list[Pod]],
        static_row: np.ndarray,
        pdbs: list[PodDisruptionBudget],
    ):
        """The batched device dry-run shared by the in-process PostFilter
        path (evaluate) and the served /preempt verb (victims_by_node):
        returns (fits_all, victims [S, N], n_victims, n_viol, max_prio,
        sum_prio, latest, slot_candidates)."""
        n_pad = nodes.padded
        k = len(nodes.vocab)
        prio = pod.effective_priority

        keep_used = np.zeros((k, n_pad), dtype=np.int64)
        keep_cnt = np.zeros(n_pad, dtype=np.int32)
        # slot -> (reprieve-ordered candidates, PDB-violating keys)
        slot_candidates: dict[int, tuple[list[Pod], set]] = {}
        max_slots = 1
        for slot, placed in placed_by_slot.items():
            if slot >= n_pad:
                continue
            lower = [q for q in placed if q.effective_priority < prio]
            for q in placed:
                if q.effective_priority >= prio:
                    keep_used[:, slot] += nodes.vocab.vectorize(
                        q.resource_request()
                    )
                    keep_cnt[slot] += 1
            if lower:
                violating, non_violating = classify_pdb_violations(
                    sort_more_important(lower), pdbs
                )
                ordered = sort_more_important(violating) + sort_more_important(
                    non_violating
                )
                slot_candidates[slot] = (ordered, {q.key for q in violating})
                max_slots = max(max_slots, len(ordered))
        # nodes with no placed pods: keep arrays stay zero

        s_pad = bucket_pow2(max_slots, floor=SLOT_PAD)
        cand_req = np.zeros((s_pad, k, n_pad), dtype=np.int64)
        cand_active = np.zeros((s_pad, n_pad), dtype=bool)
        cand_viol = np.zeros((s_pad, n_pad), dtype=bool)
        cand_prio = np.zeros((s_pad, n_pad), dtype=np.int32)
        cand_start = np.zeros((s_pad, n_pad), dtype=np.float32)
        for slot, (ordered, viol_keys) in slot_candidates.items():
            for s, q in enumerate(ordered):
                cand_req[s, :, slot] = nodes.vocab.vectorize(q.resource_request())
                cand_active[s, slot] = True
                cand_viol[s, slot] = q.key in viol_keys
                cand_prio[s, slot] = q.effective_priority
                cand_start[s, slot] = q.start_time

        req = nodes.vocab.vectorize(pod.resource_request())
        out = _preempt_scan_jit(
            jnp.asarray(nodes.allocatable),
            jnp.asarray(nodes.max_pods),
            jnp.asarray(keep_used),
            jnp.asarray(keep_cnt),
            jnp.asarray(static_row & nodes.valid),
            jnp.asarray(req),
            jnp.asarray(cand_req),
            jnp.asarray(cand_active),
            jnp.asarray(cand_viol),
            jnp.asarray(cand_prio),
            jnp.asarray(cand_start),
        )
        fits_all, victims, n_victims, n_viol, max_prio, sum_prio, latest = (
            np.asarray(x) for x in out
        )
        return (
            fits_all, victims, n_victims, n_viol, max_prio, sum_prio,
            latest, slot_candidates,
        )

    def victims_by_node(
        self,
        pod: Pod,
        nodes: NodeBatch,
        slot_names: list[str],
        placed_by_slot: dict[int, list[Pod]],
        static_row: np.ndarray,
        pdbs: list[PodDisruptionBudget] | None = None,
        candidate_slots: list[int] | None = None,
    ) -> dict[str, tuple[list[Pod], int]]:
        """Per-candidate victim sets for the served /preempt verb
        (extender.go#ProcessPreemption's nodeNameToVictims map): node name
        -> (victims in reprieve order, PDB violations). Fit-only
        semantics, same as the scalar select_victims_on_node the verb
        previously used per node — but ONE device dry-run covers every
        candidate. A node where the pod fits WITHOUT evictions stays in
        the result with an empty victim list (the wire contract keeps
        it; extender.go#ProcessPreemption treats it as a free
        candidate), while infeasible nodes drop."""
        if pod.preemption_policy == "Never":
            return {}
        pdbs = pdbs or []
        (
            fits_all, victims, n_victims, n_viol, _mx, _sm, _lt,
            slot_candidates,
        ) = self._dry_run(pod, nodes, placed_by_slot, static_row, pdbs)
        slots = (
            candidate_slots
            if candidate_slots is not None
            else list(range(len(slot_names)))
        )
        out: dict[str, tuple[list[Pod], int]] = {}
        for slot in slots:
            if not fits_all[slot]:
                continue
            ordered, _ = slot_candidates.get(slot, ([], set()))
            chosen = [q for s, q in enumerate(ordered) if victims[s, slot]]
            out[slot_names[slot]] = (chosen, int(n_viol[slot]))
        return out

    def evaluate(
        self,
        pod: Pod,
        nodes: NodeBatch,
        slot_names: list[str],
        placed_by_slot: dict[int, list[Pod]],
        static_row: np.ndarray,  # [Np] bool — pod's static feasibility
        pdbs: list[PodDisruptionBudget] | None = None,
        slot_nodes: list | None = None,  # [Np] Node|None, for full filters
        beyond_fit: bool = False,
        disabled: frozenset = frozenset(),  # profile's disabled filters
    ) -> PreemptionResult | None:
        if pod.preemption_policy == "Never":
            return None
        pdbs = pdbs or []
        n_pad = nodes.padded
        (
            fits_all, victims, n_victims, n_viol, max_prio, sum_prio,
            latest, slot_candidates,
        ) = self._dry_run(pod, nodes, placed_by_slot, static_row, pdbs)

        if beyond_fit and slot_nodes is not None:
            # Beyond-fit filters in play: a node where the pod fits with
            # ZERO fit-victims can still be the right candidate (evictions
            # may free ports / relax spread / remove anti-affinity owners),
            # so keep every fit-feasible node with at least one lower-
            # priority pod and let the full-filter oracle decide.
            has_lower = np.zeros(n_pad, dtype=bool)
            for slot in slot_candidates:
                has_lower[slot] = True
            cand_idx = np.flatnonzero(fits_all & has_lower)
        else:
            # Fit-only world: zero-victim "candidates" mean the pod fits
            # without eviction, so the solve failure was elsewhere — never
            # nominate a node and "preempt" nothing.
            cand_idx = np.flatnonzero(fits_all & (n_victims > 0))
        if cand_idx.size == 0:
            return None
        # pickOneNodeForPreemption lexicographic via numpy lexsort
        # (last key is primary)
        order = np.lexsort(
            (
                cand_idx,  # stable node order last-resort tie-break
                -latest[cand_idx],
                n_victims[cand_idx],
                sum_prio[cand_idx],
                max_prio[cand_idx],
                n_viol[cand_idx],
            )
        )
        if not (beyond_fit and slot_nodes is not None):
            best = int(cand_idx[order[0]])
            ordered, _ = slot_candidates.get(best, ([], set()))
            chosen = [q for s, q in enumerate(ordered) if victims[s, best]]
            return PreemptionResult(
                node_name=slot_names[best],
                victims=chosen,
                num_violating=int(n_viol[best]),
            )

        # Full-filter refinement (reference SelectVictimsOnNode semantics)
        # over the top-ranked candidates. Ranking comes from the fit
        # approximation; the victim sets and the final pickOneNode run on
        # exact full-filter results. refine_k bounds host cost the way the
        # reference bounds DryRunPreemption by candidate sampling.
        from ..ops.oracle.preemption import (
            pick_one_node,
            select_victims_on_node_full,
        )
        from ..ops.oracle.profile import FullOracle, make_oracle_nodes

        live = [
            (slot, slot_nodes[slot])
            for slot in range(min(len(slot_nodes), n_pad))
            if slot_nodes[slot] is not None
        ]
        oracle_idx = {slot: j for j, (slot, _) in enumerate(live)}
        oracle = FullOracle(
            make_oracle_nodes(
                [nd for _, nd in live],
                {
                    nd.name: list(placed_by_slot.get(slot, []))
                    for slot, nd in live
                },
            ),
            disabled=disabled,
        )
        refined: dict[str, object] = {}
        names_in_order: list[str] = []
        for n_tried, rank in enumerate(order):
            if n_tried >= self.refine_k and refined:
                break  # past the floor with at least one exact candidate
            slot = int(cand_idx[rank])
            if slot not in oracle_idx:
                continue
            nv = select_victims_on_node_full(
                pod, oracle_idx[slot], oracle, pdbs
            )
            if nv is None or not nv.victims:
                continue
            name = slot_names[slot]
            refined[name] = nv
            names_in_order.append(name)
        best_name = pick_one_node(refined, names_in_order)
        if best_name is None:
            return None
        nv = refined[best_name]
        return PreemptionResult(
            node_name=best_name,
            victims=list(nv.victims),
            num_violating=nv.num_violating,
        )
