"""Scalar oracle for preemption (defaultpreemption PostFilter).

Transcription of pkg/scheduler/framework/preemption/preemption.go#Evaluator
+ plugins/defaultpreemption/default_preemption.go (SURVEY.md §3.1, §8.5):

- SelectVictimsOnNode: clone node state, remove ALL pods with priority <
  incoming; if the pod still doesn't fit -> node is not a candidate. Then
  try to reprieve victims: PDB-violating candidates first, then
  non-violating, each bucket in MoreImportantPod order (priority desc,
  earlier start first); a reprieved pod is re-added if the incoming pod
  still fits alongside it. Whatever cannot be reprieved is the victim set.
- filterPodsWithPDBViolation: a candidate violates if any matching PDB has
  no disruptions left (counters decrement as non-violating candidates are
  classified).
- pickOneNodeForPreemption lexicographic: fewest PDB violations -> lowest
  highest-victim-priority -> smallest priority sum -> fewest victims ->
  latest start among highest-priority victims -> first node in list order.

Scope note (shared with the device kernel in solver/preemption.py): the
re-add feasibility check is NodeResourcesFit + pod count (the reference
reruns the full filter pipeline per reprieve, RunFilterPluginsWithNominated
Pods); static per-node feasibility of the incoming pod (taints/affinity/
nodeName) gates candidacy up front. Ports/affinity/spread interactions
with victim removal are a documented divergence to be tightened later.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from ...api.objects import Node, Pod, PodDisruptionBudget

__all__ = [
    "PodDisruptionBudget",
    "more_important",
    "sort_more_important",
    "classify_pdb_violations",
    "NodeVictims",
    "select_victims_on_node",
    "pick_one_node",
]

PREEMPT_NEVER = "Never"


def more_important(p1: Pod, p2: Pod) -> bool:
    """util.MoreImportantPod: higher priority first; tie -> earlier start
    (longer-running) first."""
    if p1.effective_priority != p2.effective_priority:
        return p1.effective_priority > p2.effective_priority
    return p1.start_time < p2.start_time


def sort_more_important(pods: Sequence[Pod]) -> list[Pod]:
    return sorted(
        pods, key=lambda p: (-p.effective_priority, p.start_time, p.key)
    )


def classify_pdb_violations(
    candidates: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> tuple[list[Pod], list[Pod]]:
    """filterPodsWithPDBViolation: (violating, non_violating); counters
    decrement as non-violating candidates claim allowance."""
    allowed = [p.disruptions_allowed for p in pdbs]
    violating: list[Pod] = []
    non_violating: list[Pod] = []
    for pod in candidates:
        matching = [i for i, pdb in enumerate(pdbs) if pdb.matches(pod)]
        if any(allowed[i] <= 0 for i in matching):
            violating.append(pod)
        else:
            for i in matching:
                allowed[i] -= 1
            non_violating.append(pod)
    return violating, non_violating


@dataclass
class NodeVictims:
    victims: list[Pod]
    num_violating: int


def select_victims_on_node(
    pod: Pod,
    node_alloc: Mapping[str, int],
    max_pods: int,
    pods_on_node: Sequence[Pod],
    pdbs: Sequence[PodDisruptionBudget] = (),
) -> NodeVictims | None:
    """Fit-only dry run. Returns None if even evicting every lower-priority
    pod cannot make room."""
    prio = pod.effective_priority
    keep = [q for q in pods_on_node if q.effective_priority >= prio]
    potential = [q for q in pods_on_node if q.effective_priority < prio]

    def fits(current: Sequence[Pod]) -> bool:
        used: dict[str, int] = {}
        for q in current:
            for k, v in q.resource_request().items():
                used[k] = used.get(k, 0) + v
        for k, v in pod.resource_request().items():
            if v and used.get(k, 0) + v > node_alloc.get(k, 0):
                return False
        return len(current) + 1 <= max_pods

    if not fits(keep):
        return None

    violating, non_violating = classify_pdb_violations(
        sort_more_important(potential), pdbs
    )
    current = list(keep)
    victims: list[Pod] = []
    num_violating = 0
    for bucket, counts in ((violating, True), (non_violating, False)):
        for q in sort_more_important(bucket):
            if fits(current + [q]):
                current.append(q)  # reprieved
            else:
                victims.append(q)
                if counts:
                    num_violating += 1
    return NodeVictims(victims=victims, num_violating=num_violating)


def pick_one_node(
    candidates: Mapping[str, NodeVictims], node_order: Sequence[str]
) -> str | None:
    """pickOneNodeForPreemption lexicographic ordering."""
    if not candidates:
        return None

    def key(name: str):
        nv = candidates[name]
        if not nv.victims:
            # a no-victim candidate wins immediately upstream
            return (0, -(1 << 62), 0, 0, float("-inf"))
        max_prio = max(q.effective_priority for q in nv.victims)
        sum_prio = sum(q.effective_priority for q in nv.victims)
        latest_start_of_top = max(
            q.start_time
            for q in nv.victims
            if q.effective_priority == max_prio
        )
        return (
            nv.num_violating,
            max_prio,
            sum_prio,
            len(nv.victims),
            -latest_start_of_top,
        )

    ordered = [n for n in node_order if n in candidates]
    return min(ordered, key=key)
