"""Fleet membership: which configured replicas are alive.

The fleet's membership model is availability against a **configured
universe** (``--fleet-replicas`` at deploy time), which is what makes
the ring's remap bound structural (fleet/ring.py): a replica joining or
leaving at runtime is a lease event, not a repartition.

Liveness rides the per-shard leases the LeaderElector satellite added
(utils/leaderelection.py ``shard=``): replica ``i`` holds
``<lease>-shard-<i>``; a peer is alive while its shard lease is held
and fresh. ``refresh_from_leases`` is the production poll; the sim
drives ``set_alive`` directly (deterministic membership transitions).
Every view change bumps ``version`` so callers know to resync their
shard-scoped caches.
"""

from __future__ import annotations

from typing import Iterable

from ..state.cluster import ApiError, ClusterState


def shard_index(universe: tuple[str, ...], replica: str) -> int:
    """A replica's shard number = its rank in the sorted universe (the
    suffix of its per-shard lease name)."""
    return universe.index(replica)


class FleetMembership:
    def __init__(self, universe: Iterable[str], self_id: str) -> None:
        self.universe = tuple(sorted(set(universe)))
        if self_id not in self.universe:
            raise ValueError(
                f"replica {self_id!r} is not in the configured universe "
                f"{self.universe}"
            )
        self.self_id = self_id
        self._alive = set(self.universe)
        self.version = 0

    def alive(self) -> tuple[str, ...]:
        return tuple(sorted(self._alive))

    def is_alive(self, replica: str) -> bool:
        return replica in self._alive

    def set_alive(self, replicas: Iterable[str]) -> bool:
        """Replace the alive view; self is always a member (a replica
        that has lost its own lease exits instead of demoting itself
        here). Returns True (and bumps version) when the view
        changed."""
        new = (set(replicas) & set(self.universe)) | {self.self_id}
        if new == self._alive:
            return False
        self._alive = new
        self.version += 1
        return True

    def mark_dead(self, replica: str) -> bool:
        if replica == self.self_id:
            return False
        return self.set_alive(self._alive - {replica})

    def mark_alive(self, replica: str) -> bool:
        return self.set_alive(self._alive | {replica})

    def refresh_from_leases(
        self,
        cluster: ClusterState,
        base_name: str,
        now: float,
        namespace: str = "kube-system",
    ) -> bool:
        """Production liveness poll: peer ``r`` (shard ``i``) is alive
        while lease ``<base>-shard-<i>`` is held by ``r`` and its
        ``renewTime + leaseDurationSeconds`` has not passed — the same
        takeover criterion LeaderElector applies. A missing lease means
        the replica never started: dead."""
        alive = {self.self_id}
        for i, replica in enumerate(self.universe):
            if replica == self.self_id:
                continue
            try:
                lease = cluster.get_lease(
                    namespace, f"{base_name}-shard-{i}"
                )
            except ApiError:
                continue
            if (
                lease.holder_identity == replica
                and now < lease.renew_time + lease.lease_duration_seconds
            ):
                alive.add(replica)
        return self.set_alive(alive)
