"""Fleet mode: the active-active scale-out tier (ISSUE 6 / ROADMAP
open item #1).

``--leader-elect`` style HA is active/passive — one process solves,
the rest idle. Fleet mode instead partitions the *cluster* across N
active scheduler replicas: each owns a deterministic shard of nodes
(fleet/ring.py — zone-keyed, balance-capped, bounded remap on
membership change), schedules the pods the ring routes to it, and
solves its shard concurrently with its peers. Cross-shard
``PodTopologySpread`` / inter-pod anti-affinity is resolved without a
global lock: replicas exchange compact occupancy rows
(fleet/occupancy.py, the host-side mirror of the device
``BatchCarriedUsage`` carry, framed by the same tensorcodec wire) and
re-validate each placement pre-assume (fleet/reconciler.py), retrying
conflicts through the scheduler's existing requeue machinery.

Wiring: set ``SchedulerConfig.fleet = FleetConfig(replica=...,
replicas=(...))``; replicas sharing a process (sim, tests, bench)
share one ``OccupancyExchange``; cross-process replicas share the same
hub over the bulk gRPC service's ``HubOp`` method
(``RemoteOccupancyExchange``, config key ``fleet.hubAddress``) with
admission kept atomic hub-side by the fenced compare-and-stage, and
each replica owns an exclusive device slice via ``fleet.meshSlice``.

The hub itself is replicated (fleet/ha.py): standby hubs consume the
primary's op log, a ``HubLease`` grants monotone fencing epochs, and
``RemoteOccupancyExchange`` takes an endpoint LIST
(``fleet.hubAddress`` accepts comma-separated "host:port"s) and fails
over with jittered backoff — a deposed primary rejects writes with the
typed ``HubDeposed`` and clients verify the epoch on every reply is
monotone, so a partitioned old primary can never accept a CAS the new
primary doesn't know about. ``SqliteHubLease`` (fleet/leasestore.py)
backs the same lease interface with one SQLite file — persisted
fencing epochs, provable multi-host offline.

The fleet BACKLOG DRAIN (fleet/drain.py, ROADMAP #5a) shards a cold
512k-pod backlog across the fleet: the hub-primary-hosted coordinator
runs the relax mega-plan once globally, partitions pods by
planned-node shard ownership, and hands each replica an epoch-fenced
drain lease; replicas drain their partitions concurrently through
their own ``drain_backlog`` slot rings (``fleet_drain_backlog``), a
dead replica's lease returns for reassignment, and the cross-shard-
constrained residual drains serialized at the end.
"""

from . import drain
from .ha import HubLease, LocalHubClient, StandbyReplicator
from .leasestore import SqliteHubLease
from .membership import FleetMembership, shard_index
from .occupancy import (
    AdmitConflict,
    COMMITTED,
    PENDING,
    ExchangeUnreachable,
    HubDeposed,
    NodeRow,
    OccupancyExchange,
    PeerView,
    PodRow,
    decode_rows,
    dispatch_hub_op,
    encode_rows,
)
from .reconciler import CrossShardReconciler
from .ring import HashRing, RingNode, ring_nodes_from
from .runtime import FleetConfig, FleetRuntime, RemoteOccupancyExchange

__all__ = [
    "AdmitConflict",
    "COMMITTED",
    "PENDING",
    "CrossShardReconciler",
    "ExchangeUnreachable",
    "RemoteOccupancyExchange",
    "FleetConfig",
    "FleetMembership",
    "FleetRuntime",
    "HashRing",
    "HubDeposed",
    "HubLease",
    "LocalHubClient",
    "NodeRow",
    "StandbyReplicator",
    "dispatch_hub_op",
    "OccupancyExchange",
    "PeerView",
    "PodRow",
    "RingNode",
    "SqliteHubLease",
    "decode_rows",
    "drain",
    "encode_rows",
    "ring_nodes_from",
    "shard_index",
]
