"""Fleet backlog drain (ISSUE 20): the pure drain-lease ledger
(fleet/drain.py), its hub hosting + op-log/snapshot replication and
return-on-retire seam, the file-backed SqliteHubLease, the per-domain
CAS scope (leg c), the fleet HBM budget split, and the end-to-end
fleet-of-N sim drive with a mid-drain replica kill."""

import pytest

from kubernetes_tpu.fleet import (
    AdmitConflict,
    LocalHubClient,
    NodeRow,
    OccupancyExchange,
    PENDING,
    PodRow,
    SqliteHubLease,
    StandbyReplicator,
    dispatch_hub_op,
    drain,
)
from kubernetes_tpu.solver.budget import split_fleet_budget
from kubernetes_tpu.utils.clock import FakeClock

KEYS = [f"default/p{i:02d}" for i in range(8)]


def _plan(nodes):
    """keys[i] planned onto nodes[i] (None = left unplaced)."""
    return dict(zip(KEYS, nodes))


ASSIGN = {"n0": "r0", "n1": "r0", "n2": "r1", "n3": "r1"}


# -- partition_backlog -------------------------------------------------------


class TestPartitionBacklog:
    def test_partitions_by_planned_node_owner_in_plan_order(self):
        planned = _plan(["n0", "n2", "n1", "n3", "n0", "n2", "n1", "n3"])
        parts, residual = drain.partition_backlog(KEYS, planned, ASSIGN)
        assert parts == {
            "r0": [KEYS[0], KEYS[2], KEYS[4], KEYS[6]],
            "r1": [KEYS[1], KEYS[3], KEYS[5], KEYS[7]],
        }
        assert residual == []

    def test_unplanned_and_unowned_nodes_fall_residual(self):
        planned = _plan(["n0", None, "n9", "n2", None, "n0", "n2", "n9"])
        parts, residual = drain.partition_backlog(KEYS, planned, ASSIGN)
        assert parts == {
            "r0": [KEYS[0], KEYS[5]],
            "r1": [KEYS[3], KEYS[6]],
        }
        # plan order preserved inside the residual too
        assert residual == [KEYS[1], KEYS[2], KEYS[4], KEYS[7]]

    def test_cross_shard_constraint_overrides_ownership(self):
        planned = _plan(["n0"] * 8)
        parts, residual = drain.partition_backlog(
            KEYS, planned, ASSIGN,
            cross_shard=lambda k: k == KEYS[3],
        )
        assert KEYS[3] in residual
        assert KEYS[3] not in parts["r0"]

    def test_gang_drains_whole_at_first_members_owner(self):
        # members planned across BOTH shards: the gang follows its
        # first planned member (splitting it would deadlock the
        # all-or-nothing barrier across two drain leases)
        planned = _plan(["n0", "n2", "n2", "n0", "n0", "n0", "n0", "n0"])
        gangs = {KEYS[1]: "g1", KEYS[2]: "g1", KEYS[3]: "g1"}
        parts, residual = drain.partition_backlog(
            KEYS, planned, ASSIGN,
            gang_of=lambda k: gangs.get(k, ""),
        )
        assert residual == []
        assert parts["r1"] == [KEYS[1], KEYS[2], KEYS[3]]

    def test_gang_with_residual_member_goes_whole_residual(self):
        planned = _plan(["n0", "n2", None, "n2", "n0", "n0", "n0", "n0"])
        gangs = {KEYS[1]: "g1", KEYS[2]: "g1", KEYS[3]: "g1"}
        parts, residual = drain.partition_backlog(
            KEYS, planned, ASSIGN,
            gang_of=lambda k: gangs.get(k, ""),
        )
        assert residual == [KEYS[1], KEYS[2], KEYS[3]]
        assert "r1" not in parts

    def test_deterministic(self):
        planned = _plan(["n0", "n2", None, "n3", "n1", None, "n2", "n0"])
        a = drain.partition_backlog(KEYS, planned, ASSIGN)
        b = drain.partition_backlog(KEYS, planned, ASSIGN)
        assert a == b


# -- the lease ledger state machine ------------------------------------------


def _two_shard_state(residual=()):
    parts, _ = drain.partition_backlog(
        KEYS[:6],
        _plan(["n0", "n2", "n1", "n3", "n0", "n2"]),
        ASSIGN,
    )
    return drain.new_state(parts, list(residual))


class TestLedger:
    def test_claim_grants_own_partition_once(self):
        st = _two_shard_state()
        lease, reassigned = drain.claim(st, "r0")
        assert not reassigned
        assert lease["kind"] == "partition"
        assert lease["keys"] == [KEYS[0], KEYS[2], KEYS[4]]
        # idempotent re-serve (a claim RPC retried after a lost reply)
        again, _ = drain.claim(st, "r0")
        assert again == lease
        # after completion the base partition is NEVER regranted
        assert drain.complete(st, "r0", lease["id"])
        assert drain.claim(st, "r0") == (None, False)

    def test_progress_scoped_to_lease_and_recorded_once(self):
        st = _two_shard_state()
        lease, _ = drain.claim(st, "r0")
        # keys outside the lease (r1's partition, non-backlog riders)
        # are ignored; duplicates count once
        n = drain.progress(
            st, "r0", [KEYS[0], KEYS[0], KEYS[1], "default/other"]
        )
        assert n == 1
        assert drain.progress(st, "r0", [KEYS[0]]) == 0
        # a replica with no granted lease records nothing
        assert drain.progress(st, "r1", [KEYS[1]]) == 0

    def test_complete_requires_own_granted_lease(self):
        st = _two_shard_state()
        lease, _ = drain.claim(st, "r0")
        assert not drain.complete(st, "r1", lease["id"])  # not yours
        assert not drain.complete(st, "r0", "L99")  # no such lease
        assert drain.complete(st, "r0", lease["id"])
        assert not drain.complete(st, "r0", lease["id"])  # not granted

    def test_return_leases_orphans_outstanding_and_unclaimed_base(self):
        st = _two_shard_state()
        lease, _ = drain.claim(st, "r1")
        drain.progress(st, "r1", [lease["keys"][0]])
        # r1 dies mid-lease; r0 never claimed its base partition
        assert drain.return_leases(st, "r1") == 2
        assert drain.return_leases(st, "r0") == 3
        s = drain.status(st)
        assert s["orphans"] == 5 and s["granted"] == 0
        # neither dead replica's base partition is ever regranted
        assert st["claimed"]["r0"] == ""

    def test_reassignment_adopts_orphans_exactly_once(self):
        st = _two_shard_state()
        lease, _ = drain.claim(st, "r1")
        done_key, *outstanding = lease["keys"]
        drain.progress(st, "r1", [done_key])
        drain.return_leases(st, "r1")
        adopted, reassigned = drain.claim(st, "r0")
        # r0 gets its OWN partition first (claim order), orphans next
        assert adopted["kind"] == "partition"
        drain.complete(st, "r0", adopted["id"])
        adopted, reassigned = drain.claim(st, "r0")
        assert reassigned and adopted["kind"] == "orphan"
        assert adopted["keys"] == outstanding  # done key NOT re-drained
        assert st["reassigned"] == 1
        # the zombie's late progress report lands on a RETURNED lease:
        # ignored, so the orphan claimant can't be double-counted
        assert drain.progress(st, "r1", outstanding) == 0

    def test_residual_serialized_behind_all_shard_leases(self):
        st = _two_shard_state(residual=[KEYS[6], KEYS[7]])
        l0, _ = drain.claim(st, "r0")
        # r1 hasn't claimed: no residual yet (r0's next claim is None)
        drain.complete(st, "r0", l0["id"])
        assert drain.claim(st, "r0") == (None, False)
        l1, _ = drain.claim(st, "r1")
        # r1's shard lease still granted: residual stays gated
        assert drain.claim(st, "r0") == (None, False)
        drain.complete(st, "r1", l1["id"])
        res, _ = drain.claim(st, "r0")
        assert res["kind"] == "residual"
        assert res["keys"] == [KEYS[6], KEYS[7]]
        # granted exactly once, to ONE claimant
        assert drain.claim(st, "r1") == (None, False)

    def test_outstanding_keys_and_status_counts(self):
        st = _two_shard_state(residual=[KEYS[6]])
        lease, _ = drain.claim(st, "r0")
        drain.progress(st, "r0", [KEYS[0]])
        out = drain.outstanding_keys(st)
        assert KEYS[0] not in out and KEYS[6] in out
        s = drain.status(st)
        assert s["pods"] == 7 and s["done"] == 1
        assert s["outstanding"] == 6 and not s["complete"]


# -- hub hosting: fencing, replication, return-on-retire ---------------------


def _hub_with_drain(**hub_kw):
    hub = OccupancyExchange(**hub_kw)  # standalone: permanently primary
    parts, residual = (
        {"r0": [KEYS[0], KEYS[1]], "r1": [KEYS[2], KEYS[3]]},
        [KEYS[4]],
    )
    hub.drain_init("r0", parts, residual, membership_version=7)
    return hub


class TestHubDrainOps:
    def test_init_claim_progress_complete_roundtrip(self):
        hub = _hub_with_drain()
        st = hub.drain_status()
        assert st["active"] and st["pods"] == 5 and st["residual"] == 1
        lease = hub.drain_claim("r0")
        assert lease["keys"] == [KEYS[0], KEYS[1]]
        assert hub.drain_progress("r0", [KEYS[0], KEYS[1]]) == 2
        assert hub.drain_complete("r0", lease["id"])
        assert hub.drain_status()["done"] == 2

    def test_second_init_rejected_until_ledger_drains_dry(self):
        hub = _hub_with_drain()
        with pytest.raises(AdmitConflict):
            hub.drain_init("r0", {"r0": ["default/x"]}, [])
        # drain everything dry, then a new global plan may land
        for rid in ("r0", "r1"):
            lease = hub.drain_claim(rid)
            hub.drain_progress(rid, lease["keys"])
            hub.drain_complete(rid, lease["id"])
        res = hub.drain_claim("r0")
        hub.drain_progress("r0", res["keys"])
        hub.drain_complete("r0", res["id"])
        assert hub.drain_status()["complete"]
        assert hub.drain_init("r0", {"r0": ["default/x"]}, [])["pods"] == 1

    def test_retire_returns_lease_for_reassignment(self):
        from kubernetes_tpu import metrics

        hub = _hub_with_drain()
        lease = hub.drain_claim("r1")
        hub.drain_progress("r1", [lease["keys"][0]])
        before = (
            metrics.fleet_drain_lease_reassignments_total._value.get()
        )
        hub.retire("r1")
        st = hub.drain_status()
        assert st["orphans"] == 1 and st["granted"] == 0
        # the zombie's post-retire drain writes are fenced like rows
        with pytest.raises(AdmitConflict):
            hub.drain_progress("r1", [lease["keys"][1]])
        adopted = hub.drain_claim("r0")
        assert adopted["kind"] == "partition"
        hub.drain_complete("r0", adopted["id"])
        adopted = hub.drain_claim("r0")
        assert adopted["kind"] == "orphan"
        assert adopted["keys"] == [lease["keys"][1]]
        assert (
            metrics.fleet_drain_lease_reassignments_total._value.get()
            == before + 1
        )

    def test_ledger_replicates_incrementally_and_via_snapshot(self):
        hub = _hub_with_drain()
        standby = OccupancyExchange(hub_id="hub-b")
        standby._role = "standby"
        rep = StandbyReplicator(standby, LocalHubClient(hub))
        lease = hub.drain_claim("r0")
        hub.drain_progress("r0", [KEYS[0]])
        hub.retire("r1")
        hub.drain_complete("r0", lease["id"])
        rep.poll()
        # bit-identical ledger through the incremental "drain" op
        # replay (no 512k-key state shipped wholesale)
        assert standby._drain == hub._drain
        # the fence-exempt read surfaces serve from the standby too:
        # 'how far did the drain get' is a post-failover question
        assert (
            standby.drain_outstanding_keys()
            == hub.drain_outstanding_keys()
        )
        # a standby further behind than the SOURCE's retained op-log
        # window re-joins via snapshot — the ledger rides it
        small = _hub_with_drain(oplog_capacity=2)
        lease = small.drain_claim("r0")
        small.drain_progress("r0", [KEYS[0]])
        small.drain_complete("r0", lease["id"])
        late = OccupancyExchange(hub_id="hub-c")
        late._role = "standby"
        rep2 = StandbyReplicator(late, LocalHubClient(small))
        rep2.poll()
        assert rep2.snapshots_installed == 1
        assert late._drain == small._drain

    def test_drain_status_inactive_without_ledger(self):
        hub = OccupancyExchange()
        assert hub.drain_status() == {"active": False}
        assert hub.drain_outstanding_keys() == []
        assert hub.drain_claim("r0") is None
        assert hub.drain_progress("r0", [KEYS[0]]) == 0
        assert not hub.drain_complete("r0", "L1")

    def test_drain_ops_ride_the_hub_op_dispatch(self):
        hub = _hub_with_drain()
        out = dispatch_hub_op(hub, "drain_status", {"replica": "r0"})
        assert out["status"]["pods"] == 5
        out = dispatch_hub_op(hub, "drain_claim", {"replica": "r0"})
        lid = out["lease"]["id"]
        out = dispatch_hub_op(
            hub, "drain_progress",
            {"replica": "r0", "keys": [KEYS[0]]},
        )
        assert out["done"] == 1
        out = dispatch_hub_op(
            hub, "drain_complete", {"replica": "r0", "lease": lid},
        )
        assert out["ok"] is True


# -- SqliteHubLease (leg b): the contract tests run against both
# backends in tests/test_hub_ha.py; here, what only sqlite has -------------


class TestSqliteHubLease:
    def test_state_survives_reopen(self, tmp_path):
        path = str(tmp_path / "lease.db")
        clock = FakeClock()
        lease = SqliteHubLease(path, clock=clock, duration_s=2.0)
        assert lease.try_acquire("a") == 1
        clock.advance(3.0)
        assert lease.try_acquire("b") == 2  # takeover bumped the epoch
        # a hub process restart re-opens the SAME file: holder and
        # epoch are durable, so a restarted incumbent renews at its
        # epoch instead of reading as a fresh failover
        reopened = SqliteHubLease(path, clock=clock, duration_s=2.0)
        assert reopened.epoch == 2 and reopened.holder == "b"
        assert reopened.try_acquire("b") == 2
        assert reopened.valid("b")

    def test_release_is_durable_and_keeps_epoch(self, tmp_path):
        path = str(tmp_path / "lease.db")
        clock = FakeClock()
        lease = SqliteHubLease(path, clock=clock, duration_s=2.0)
        assert lease.try_acquire("a") == 1
        lease.release("a")
        reopened = SqliteHubLease(path, clock=clock, duration_s=2.0)
        assert not reopened.valid("a")
        # an explicit release expires WITHOUT rewinding the epoch: the
        # successor's grant still fences the old holder's writes
        assert reopened.try_acquire("b") == 2

    def test_epoch_grant_feeds_hub_promotion(self, tmp_path):
        clock = FakeClock()
        lease = SqliteHubLease(
            str(tmp_path / "lease.db"), clock=clock, duration_s=2.0
        )
        hub = OccupancyExchange(
            clock=clock, hub_id="hub-a", lease=lease
        )
        assert hub.try_promote() == 1
        hub.stage(
            "r0",
            PodRow(
                pod="default/p", node="n1", zone="z0",
                namespace="default", labels=(), state=PENDING,
            ),
        )
        assert hub.hub_epoch == 1


# -- per-domain CAS versioning (leg c) ---------------------------------------


def _spread_row(pod="default/p", zone="z0", labels=(("app", "x"),)):
    return PodRow(
        pod=pod, node="n1", zone=zone, namespace="default",
        labels=labels, state=PENDING,
    )


class TestDomainScopedCas:
    def _hub(self):
        hub = OccupancyExchange()
        hub.publish_nodes("r0", [NodeRow("n0", "z0"), NodeRow("n1", "z0")])
        hub.publish_nodes("r1", [NodeRow("n2", "z1")])
        return hub, hub.version

    def test_label_free_other_zone_row_is_not_a_conflict(self):
        hub, v = self._hub()
        hub.stage("r1", _spread_row(pod="default/q", zone="z1", labels=()))
        # the hub-wide CAS charges the admit a re-fetch round for an
        # interleaving that provably cannot touch its admission …
        with pytest.raises(AdmitConflict):
            hub.compare_and_stage("r0", _spread_row(), v)
        # … the domain-scoped CAS does not
        assert hub.compare_and_stage(
            "r0", _spread_row(), v, domain_scope=True
        ) > 0

    def test_same_zone_row_still_conflicts(self):
        hub, v = self._hub()
        hub.stage("r1", _spread_row(pod="default/q", zone="z0", labels=()))
        with pytest.raises(AdmitConflict):
            hub.compare_and_stage(
                "r0", _spread_row(), v, domain_scope=True
            )

    def test_label_bearing_row_conflicts_every_domain(self):
        hub, v = self._hub()
        # a label-bearing row can match ANY selector: hub-wide floor
        hub.stage("r1", _spread_row(pod="default/q", zone="z1"))
        with pytest.raises(AdmitConflict):
            hub.compare_and_stage(
                "r0", _spread_row(), v, domain_scope=True
            )

    def test_membership_mutation_conflicts_every_domain(self):
        hub, v = self._hub()
        hub.retire("r1")  # shard inventory changed under the view
        with pytest.raises(AdmitConflict):
            hub.compare_and_stage(
                "r0", _spread_row(), v, domain_scope=True
            )

    def test_drain_ledger_mutations_do_not_conflict(self):
        hub, v = self._hub()
        hub.drain_init("r0", {"r0": [KEYS[0]]}, [])
        hub.drain_claim("r0")
        hub.drain_progress("r0", [KEYS[0]])
        assert hub.version > v  # the ledger DID move the hub version
        # … but ledger traffic can't interfere with row admission, so
        # a drain in flight doesn't tax every constrained admit with
        # re-fetch rounds (the leg-c measurement's point)
        assert hub.compare_and_stage(
            "r0", _spread_row(), v, domain_scope=True
        ) > 0
        with pytest.raises(AdmitConflict):
            hub.compare_and_stage("r0", _spread_row(pod="default/q"), v)


# -- fleet HBM budget split --------------------------------------------------


def test_split_fleet_budget_even_with_low_index_remainder():
    assert split_fleet_budget(100, 1) == 100
    assert split_fleet_budget(100, 4) == 25
    assert split_fleet_budget(10, 3, replica_index=0) == 4
    assert split_fleet_budget(10, 3, replica_index=1) == 3
    assert split_fleet_budget(10, 3, replica_index=2) == 3
    # shares cover the total exactly
    assert sum(split_fleet_budget(10, 3, replica_index=i) for i in range(3)) == 10
    assert split_fleet_budget(2, 8) == 1  # never zero


# -- the fleet-of-N sim drive (mid-drain kill, exactly-once) -----------------


def test_fleet_backlog_drain_sim_survives_mid_drain_kill():
    from kubernetes_tpu.sim.fleet import run_fleet_sim

    res = run_fleet_sim("fleet_backlog_drain", seed=0, cycles=12)
    assert res.summary["violations"] == 0
    fd = res.summary["fleet_drain"]
    assert fd["pods"] > 0 and fd["partitions"] >= 2
    assert fd["residual"] > 0  # the serialized cohort engaged
    assert fd["leases_reassigned"] >= 1  # the kill returned a lease
    assert fd["lost"] == 0 and fd["double_bind"] == 0
    res2 = run_fleet_sim("fleet_backlog_drain", seed=0, cycles=12)
    assert res2.journal_digests == res.journal_digests
