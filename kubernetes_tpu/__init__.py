"""kubernetes_tpu — a TPU-native batched pod→node scheduler.

A from-scratch reimplementation of Kubernetes' scheduling capability
(reference: wwwtyro/kubernetes, a fork of kubernetes/kubernetes), redesigned
for TPU: the serial per-pod Filter/Score loop becomes dense pods×nodes
feasibility-mask + score-matrix solves compiled by XLA, with Pallas kernels
for the irregular hot paths, exposed behind the Scheduling Framework plugin
shapes and the scheduler-extender webhook protocol.

Package map (SURVEY.md §8):
- ``api``       — core/v1 object subset, Quantity, label selectors (L0/L1)
- ``tensorize`` — API objects -> padded device tensors (the tensor schema)
- ``ops``       — plugin kernels (Fit, BalancedAllocation, spread, affinity,
  taints, ...) + NumPy oracles for parity testing
- ``solver``    — exact-parity lax.scan solver and single-shot auction mode
- ``state``     — cluster-state service (apiserver stand-in), scheduler
  cache (assume/forget/generations), scheduling queue
- ``server``    — scheduler-extender webhook (aiohttp) + bulk gRPC path
- ``config``    — KubeSchedulerConfiguration mirror
- ``metrics``   — Prometheus metrics with upstream names
- ``obs``       — scheduling trace layer: spans, per-pod decision
  journal, flight recorder, explain CLI
- ``parallel``  — device-mesh sharding of the pods×nodes solve
"""

import logging as _logging

# library practice: no output unless an application configures handlers
# (cli.py serve installs the structured formatter via utils/logging.py)
_logging.getLogger(__name__).addHandler(_logging.NullHandler())

__version__ = "0.1.0"
