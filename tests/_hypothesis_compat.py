"""Optional-hypothesis shim: the sandbox image ships without
``hypothesis``, and a module-level import error takes every OTHER test
in the file down with it at collection. Import the property-testing
surface from here instead; when hypothesis is missing, ``@given`` tests
skip individually at runtime and the rest of the module still runs.
"""

from __future__ import annotations

try:  # pragma: no cover - exercised implicitly either way
    from hypothesis import assume, given, note, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    import pytest

    HAVE_HYPOTHESIS = False

    def given(*_a, **_k):
        def deco(fn):
            # deliberately NOT functools.wraps: pytest would follow
            # __wrapped__ to the original signature and demand fixtures
            # for the strategy-bound parameters
            def wrapper(*_args, **_kwargs):  # tolerates self on methods
                pytest.skip("hypothesis not installed")

            wrapper.__name__ = fn.__name__
            wrapper.__doc__ = fn.__doc__
            return wrapper

        return deco

    def settings(*_a, **_k):
        def deco(fn):
            return fn

        return deco

    def assume(_cond):  # never reached: @given already skipped
        return True

    def note(_msg):
        return None

    class _Strategy:
        """Inert stand-in: strategy constructors are evaluated at module
        import (inside @given(...) argument lists), so they must build
        without hypothesis; combinator methods chain to keep complex
        module-level expressions importable."""

        def __call__(self, *a, **k):
            return self

        def __getattr__(self, _name):
            return self

    class _StModule:
        def __getattr__(self, _name):
            return _Strategy()

    st = _StModule()

__all__ = [
    "HAVE_HYPOTHESIS",
    "assume",
    "given",
    "note",
    "settings",
    "st",
]
