"""The scheduler orchestrator: batch-pop pods, one device solve, bind.

This is the TPU-shaped replacement of the reference's Scheduler object + run
loop (pkg/scheduler/scheduler.go#Scheduler.Run +
schedule_one.go#scheduleOne/#schedulingCycle/#bindingCycle):

    watch events ──> cache / queue            (eventhandlers.go semantics)
    pop_batch(K) ──> snapshot.update(cache)   (UpdateSnapshot, dirty columns)
              └──> exact solver (lax.scan over the K pods, dense over nodes)
    per assignment: assume -> bind -> finish_binding
                    bind failure -> forget + requeue with backoff
    infeasible    : AddUnschedulableIfNotPresent (+ nominated-node machinery
                    once preemption lands)

The assume/forget protocol and its crash-safety story carry over unchanged
(SURVEY §6.3): the solver holds no durable state — cache + snapshot rebuild
from the state service on restart.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import metrics
from .api.objects import Pod
from .framework.interface import CycleState, StatusCode
from .framework.runtime import WaitingPod
from .obs.span import _NOOP as _NOOP_SPAN
from .resilience import (
    ACT_BISECT,
    ACT_DESCEND,
    ACT_REBUILD,
    TIER_HOST,
    TIER_MESH,
    SolveCorruptError,
    SolveResilience,
    SolverFaultError,
    SolverReadError,
    build_ladder,
    host_greedy_assign,
    tier_device_context as _tier_device_context,
    validate_assignments,
)
from .server.extender_client import ExtenderError
from .solver.exact import (
    DeferredAssignments,
    ExactSolver,
    ExactSolverConfig,
    SessionDrainRequired,
)
from .solver.preemption import PreemptionEvaluator
from .state.cache import SchedulerCache
from .state.cluster import ApiError, ClusterState, Event
from .state.claim_allocator import ClaimAllocationError
from .state.volume_binder import VolumeBindingError
from .state.queue import PriorityQueue, QueuedPodInfo
from .state.snapshot import Snapshot
from .tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
    trivial_port_tensors,
)
from .tensorize.interpod import build_interpod_tensors
from .tensorize.spread import build_spread_tensors
from .tensorize.schema import build_pod_batch
from .utils.clock import Clock


@dataclass
class SchedulerConfig:
    batch_size: int = 1024  # max pods per device solve
    solver: ExactSolverConfig = field(default_factory=ExactSolverConfig)
    assume_ttl: float = 30.0
    # RTT-hiding batch split for run_pipelined: a popped batch may be
    # dispatched as up to K chained sub-solves so the assignment read of
    # sub-batch i overlaps the solve of i+1 (only the last read pays an
    # un-hidden tunnel round trip). 0 = adaptive (split when the
    # estimated device solve time exceeds the estimated read RTT, from
    # per-batch EWMAs); 1 = never split; >1 = fixed cap per batch.
    pipeline_split: int = 0
    # streaming dispatcher (run_streaming): max dispatched-but-unapplied
    # batches in the device-side work ring. Popped batches tensorize,
    # stream down, and CHAIN on the previous batch's device-resident
    # occupancy carry (ExactSolver stream carry) while their deferred
    # assignment reads drain through the completion thread — the host
    # pays an un-hidden tunnel round trip once per ring drain (one per
    # event-fence in steady state), not once per batch. Depth bounds
    # both HBM held by in-flight solves and the bind latency a pod can
    # accrue behind later dispatches.
    stream_depth: int = 4
    # backlog drain (drain_backlog, ISSUE 12): pods per drain chunk fed
    # through the streaming ring against the resident session. 0 = plan
    # from the HBM budget model (solver/budget.py) starting at
    # batch_size; the planner halves group-aligned until the chunk's
    # per-device estimate fits the budget (auto-split instead of OOM).
    backlog_chunk_pods: int = 0
    # per-device HBM budget the drain planner asserts chunk shapes
    # against. 0 = auto (PJRT bytes_limit, else the conservative
    # solver/budget.py default floor).
    hbm_budget_bytes: int = 0
    # mega-planner warm-start for drain_backlog (ISSUE 19): before the
    # first chunk pops, a convex-relaxation solve (solver/relax.py)
    # over the whole backlog ranks the activeQ so pods the relaxed
    # plan co-locates pop adjacently and chunks pack against
    # pre-fitted capacity instead of re-discovering it chunk by
    # chunk. Priority stays the primary queue key — the rank only
    # permutes pods within a priority band (queue.reorder_active).
    backlog_warm_start: bool = False
    # defaultpreemption: run the PostFilter dry-run for unschedulable pods
    enable_preemption: bool = True
    # node-axis mesh for the device solve (parallel/sharding.py): number
    # of devices to shard the node axis over. 0 = all visible devices,
    # 1 = force the single-device (unsharded) path, N > 1 = the first
    # min(N, visible) devices. A resolved count of 1 is the unsharded
    # path either way. The mesh threads through BOTH scheduling loops —
    # overlap, carry, and sync batches all dispatch sharded — and
    # results are bit-exactly device-count invariant
    # (tests/test_sharding.py). Note for tier-1: conftest forces 8
    # virtual CPU devices, so default-config Scheduler tests exercise
    # the SHARDED path; the UNSHARDED path keeps coverage through the
    # sim suite (SimHarness pins mesh_devices=1), the direct-solver
    # parity tests (ExactSolver defaults to mesh=None), and the
    # mesh_devices=1 arms of the equivalence tests.
    mesh_devices: int = 0
    # per-replica EXCLUSIVE mesh slice (fleet device-tier scale-out;
    # config key fleet.meshSlice = "rank/count"): (rank, count) cuts
    # the visible device list into count contiguous equal slices and
    # this scheduler dispatches ONLY against slice rank, so N fleet
    # replicas on one host own disjoint device sets (a 1-device slice
    # still builds a 1-way mesh — the mesh is what pins the device).
    # mesh_devices applies within the slice. None = no slice (the
    # sole-owner scheduler).
    mesh_slice: tuple | None = None
    # multi-profile (profile.NewMap): schedulerName -> solver config for
    # that profile; pods whose schedulerName matches no profile are ignored
    # at queue-add, like the reference's frameworkForPod miss. None = the
    # single default profile using `solver`.
    profiles: dict[str, ExactSolverConfig] | None = None
    # component-base/featuregate analog (--feature-gates); None = defaults
    feature_gates: object = None
    # KubeSchedulerConfiguration.extenders[] (config/types.py#Extender):
    # consulted during each solve via the outbound HTTP client
    # (server/extender_client.py) — filter/prioritize verdicts fold into
    # the per-class device tables; a bind-verb extender owns the binding
    extenders: tuple = ()
    # out-of-tree Scheduling Framework plugins (framework/interface.py),
    # classified by the extension-point protocols each implements:
    # Filter/Score (+ PreFilter incl. PreFilterResult allowlists) fold
    # into the per-class device tables each batch
    # (framework/runtime.py#fold_out_of_tree); PreEnqueue/QueueSort hook
    # the scheduling queue; PostFilter runs on the failure path after
    # default preemption; Reserve/Permit/PreBind/PostBind run host-side
    # around the bind, with Permit's WaitingPods map parking pods across
    # cycles — the in-process plugin registration point of SURVEY §8.2.
    out_of_tree_plugins: tuple = ()
    # observability (kubernetes_tpu/obs): an ObsConfig enabling span
    # tracing and/or the per-pod decision journal + flight recorder.
    # None = all off; the hot path then pays one attribute check per
    # would-be span and zero journal work.
    obs: object = None
    # degraded-mode solve resilience (kubernetes_tpu/resilience): a
    # ResilienceConfig tuning the fallback ladder (sharded mesh →
    # single device → CPU backend → pure-host serial greedy), the
    # per-profile circuit breaker in front of it, pre-apply output
    # validation, and the poison-batch bisection quarantine. None =
    # defaults (the layer is always on — it only acts on failures, so
    # the fault-free hot path is unchanged).
    resilience: object = None
    # fleet mode (kubernetes_tpu/fleet): a FleetConfig making this
    # scheduler ONE active replica of an N-way fleet. The replica's
    # informer stream is shard-filtered (its cache and snapshot hold
    # only the nodes its ring partition owns, and only the pending
    # pods the ring routes to it), every solved placement passes the
    # cross-shard occupancy admission before it is assumed, and
    # label-bearing placements are published to the fleet's occupancy
    # exchange. None = the classic sole-owner scheduler.
    fleet: object = None
    # process-lifecycle identity: which incarnation of this scheduler
    # role this process is. 1 = a first start; > 1 = a RESTART after a
    # crash — the cold-start recovery pass then treats cluster truth as
    # the wreck of a predecessor: unbound pods are re-adopted AND
    # terminally journaled `recovered` (so journal completeness holds
    # across incarnations), half-committed occupancy (claim
    # reservations for unbound pods, stale fleet pending rows) is
    # rolled back, and quarantine/breaker state deliberately RESETS
    # (the restart may be on healed hardware; a genuinely poison pod
    # re-quarantines through the ordinary bisection path within one
    # batch — tested).
    incarnation: int = 1
    # continuous rebalancer (kubernetes_tpu/rebalance): a
    # RebalanceConfig enabling the background defragmentation loop —
    # when the queues go idle and the interval elapses, detect
    # fragmentation from the snapshot, plan a consolidation target with
    # the pack-objective auction, and execute a bounded (churn-budget,
    # PDB-gated, fenced) migration plan through the eviction
    # subresource. None = off. Fleet replicas rebalance shard-scoped
    # (their cache IS their shard); a fence-revoked zombie incarnation
    # skips every pass.
    rebalance: object = None
    # closed-loop hot-path auto-tuning (kubernetes_tpu/tuning): a
    # TuningConfig enabling the online controllers that drive the
    # hot-path knobs (drain chunk size, stream_depth, pipeline_split,
    # fleet write-behind flush batch) from the measured counters —
    # bounded hill-climbing with hysteresis and settle detection, under
    # hard guardrails (a proposed drain chunk must pass the HBM budget
    # model before it is ever applied; stream-depth changes apply only
    # at ring-drain boundaries). None = static knobs. To pin ONE knob
    # while tuning the rest, set its config value and drop it from
    # TuningConfig.knobs.
    tuning: object = None
    # commit fencing (state/cluster.py fencing tokens): the lease role
    # this scheduler's binds are fenced under. The incarnation acquires
    # a fresh token at startup — superseding any predecessor — and
    # every bind carries it; a revoked/superseded token means the state
    # service rejects the commit with Conflict (scheduler_commit_fenced
    # _total) so a zombie can never double-bind. None = no fencing
    # (single-owner deployments that never restart in place); fleet
    # replicas default to their per-shard lease name.
    fence_role: str | None = None
    # gang scheduling (kubernetes_tpu/gang): a GangConfig enabling
    # all-or-nothing pod groups (the `scheduling.x-k8s.io/pod-group`
    # label + min-member annotation) — a gang's members pop as a unit,
    # solve through the ordinary chained sub-batch machinery, stage
    # through assume/Reserve/Permit like any pod, and then COMMIT AS
    # ONE: every member binds through ClusterState.bind_gang or every
    # member's placement is released and the gang requeues with a
    # `gang_incomplete` journal record. Carries the heterogeneity
    # objective too (gang/throughput.py). None = off (zero hot-path
    # cost beyond one attribute check per batch).
    gang: object = None


class _Rejected(Exception):
    """An out-of-tree Reserve/PreBind plugin returned a non-success
    status: the binding rolls back and the pod requeues with backoff."""


def _node_change_could_help(old, new) -> bool:
    """eventhandlers.go#nodeSchedulingPropertiesChange: allocatable, labels,
    taints, or spec.unschedulable changes can unblock parked pods; pure
    status-heartbeat updates cannot."""
    return (
        old.allocatable != new.allocatable
        or old.labels != new.labels
        or old.taints != new.taints
        or old.unschedulable != new.unschedulable
    )


@dataclass
class BatchResult:
    scheduled: list[tuple[str, str]] = field(default_factory=list)  # (pod, node)
    unschedulable: list[str] = field(default_factory=list)
    bind_failures: list[tuple[str, str]] = field(default_factory=list)  # (pod, err)
    # pods the poison-batch bisection quarantined this cycle: their
    # solve failure is isolated and terminal-journaled; they re-admit
    # after a TTL'd backoff (kubernetes_tpu/resilience)
    quarantined: list[str] = field(default_factory=list)
    # (pod, source node, target node) per rebalancer eviction this
    # cycle (kubernetes_tpu/rebalance): the pod re-entered the queue
    # with a nominated hint — the migration completes in later cycles
    rebalance_evictions: list[tuple[str, str, str]] = field(
        default_factory=list
    )
    # (pod, nominated node, victim keys) per successful preemption
    preemptions: list[tuple[str, str, list[str]]] = field(default_factory=list)
    # pod keys whose gang round failed all-or-nothing this cycle: their
    # staged placements were released and they requeued as a unit with
    # a `gang_incomplete` journal record (kubernetes_tpu/gang)
    gang_released: list[str] = field(default_factory=list)
    solve_seconds: float = 0.0
    host_seconds: float = 0.0
    # per-pod schedule latency (pop -> bind committed), for the p99 metric
    latencies: list[float] = field(default_factory=list)
    # per-pod end-to-end latency (first queue entry -> bind committed, on
    # the scheduler clock) — the open-loop sustained benchmark's p99
    e2e_latencies: list[float] = field(default_factory=list)
    # perf_counter when this batch's bindings finished committing; lets
    # throughput collectors sample pods/s across overlapped batches
    completed_at: float = 0.0

    @property
    def progressed(self) -> bool:
        """Did this cycle do ANY work a drive loop should keep ticking
        for? One definition for every drain/settle/bench loop, so a new
        outcome field can't silently go missing from some call sites."""
        return bool(
            self.scheduled
            or self.unschedulable
            or self.bind_failures
            or self.quarantined
            or self.rebalance_evictions
            or self.gang_released
        )


@dataclass
class BacklogDrainReport:
    """What one ``Scheduler.drain_backlog`` pass did, for the bench
    ladder, the sim footer, and operators (the same numbers back the
    ``scheduler_backlog_*`` metrics). ``results`` holds the underlying
    per-chunk BatchResults so callers can fold them into their own
    accounting (the sim's bind tracker, the bench's latency pool)."""

    pods: int = 0  # backlog size at drain start
    drained: int = 0  # pods bound by this pass
    unschedulable: int = 0
    chunks: int = 0  # streaming batches dispatched
    chunk_pods: int = 0  # planned chunk size (post budget splits)
    # chunk size at drain end when the auto-tuner governed the knob
    # (kubernetes_tpu/tuning); 0 = untuned (chunk_pods held throughout)
    final_chunk_pods: int = 0
    budget_splits: int = 0  # halvings the HBM planner took
    budget_bytes: int = 0  # per-device budget asserted against
    drain_seconds: float = 0.0
    pods_per_sec: float = 0.0
    p99_e2e_latency_s: float = 0.0  # first queue entry -> bind commit
    median_chunk_solve_s: float = 0.0  # per the ladder-#10 convention
    stream_chained_batches: int = 0  # cross-batch carry chains engaged
    chain_fraction: float = 0.0  # chained / (chunks - 1)
    estimated_per_device_bytes: int = 0  # HBM model, resident worst case
    estimated_h2d_bytes: int = 0  # HBM model's predicted upload total
    measured_h2d_bytes: int = 0  # h2d counter delta over the drain
    # mega-planner warm-start (ISSUE 19): activeQ entries re-keyed by
    # the relaxed plan's rank (0 = warm-start off or nothing ranked)
    warm_start_ranked: int = 0
    relax_iterations: int = 0  # dual-ascent iterations the warm-start ran
    relax_residual: float = 0.0  # final relative-overcommit residual
    results: list = field(default_factory=list)


@dataclass
class _PreparedGroup:
    """Everything one profile sub-batch needs between tensorization and
    result application, so the two phases can run on opposite sides of a
    deferred device read (run_pipelined). For the synchronous path the
    phases run back to back and this is pure plumbing."""

    profile: str
    infos: list
    pods: list
    cycle_offsets: list
    base_cycle: int
    t0: float  # cycle start (per-pod latency base)
    gs: float  # tensorize start (attempt-duration base)
    batch: object
    pbatch: object
    static: object
    ports: object
    spread: object
    interpod: object
    nominated: object
    nominated_slot: object
    slot_nodes: list
    names: list  # snapshot slot->name mapping AT PREP TIME (fence-stable)
    volume_ctx: object
    services: list
    dra_active: bool
    fence: int = 0  # _conflict_seq INSIDE the tensorize lock (the snapshot
    # consistency point — capturing it any later would mask events landing
    # between lock release and dispatch; review-caught)
    # the occupancy fence (_occupancy_seq at tensorize time): bumped by
    # events only HARD-shaped batches are sensitive to — assigned-pod
    # deletes / label changes that free or re-key port/spread/interpod
    # occupancy, external DRA claim writes, waiting-pod rollbacks.
    # (Nominator-map changes deliberately do NOT bump it: nominated load
    # is advisory, and our own preemption nominations land mid-apply —
    # see _ingest_event.) Plain fit batches ignore it (the device fit
    # carry absorbs frees conservatively), so delete-churn cannot
    # degrade the plain pipeline.
    occ_fence: int = 0
    occ_sensitive: bool = False  # batch reads occupancy/ctx the occ
    # fence guards (ports/spread/interpod/volumes/DRA/nominated)
    step: int = 0  # the batch's span/trace id (Scheduler._trace_step)
    tensorize_seconds: float = 0.0  # host prep cost (set at dispatch)
    unsched_reason: dict = field(default_factory=dict)
    dra_prefold: dict = field(default_factory=dict)
    # pre-apply validation accumulator (resilience.validate_assignments):
    # per-slot usage this prep's already-validated flights placed, the
    # host mirror of the device-resident chain carry. Built lazily on
    # the first validated flight.
    validated_usage: object = None
    # tensorize-duration metrics observed (once per prep: ladder-rung
    # retries reuse the prep, and re-observing would inflate the
    # tensorize/PreFilter histograms exactly when operators are
    # reading them to diagnose an outage)
    timing_observed: bool = False


@dataclass
class _InFlightSolve:
    """A dispatched solve whose assignments may not have been read yet.
    Its conflict fence is ``prep.fence`` — captured inside the tensorize
    lock, NOT at dispatch (re-reading _conflict_seq any later would mask
    events landing between lock release and dispatch).

    A chained sub-batch solve (the RTT-hiding batch split) shares one
    prep with its siblings and covers only prep pods [lo, hi); the
    unsplit case is the trivial slice [0, None). ``tensorize_share`` is
    the portion of the shared tensorize cost this flight reports (full
    for the first sub-flight, 0 for the rest)."""

    prep: _PreparedGroup
    handle: object  # np.ndarray (sync) | DeferredAssignments (pipelined)
    dispatch_seconds: float
    read_seconds: float = 0.0  # blocking device-read wait (set at apply)
    lo: int = 0
    hi: int | None = None
    tensorize_share: float | None = None  # None = prep.tensorize_seconds

    def infos(self) -> list:
        return self.prep.infos[self.lo : self.hi]

    def pods(self) -> list:
        return self.prep.pods[self.lo : self.hi]

    def cycle_offsets(self) -> list:
        return self.prep.cycle_offsets[self.lo : self.hi]

    # sanctioned deferred-read point (analysis/registry.py) — the ONE
    # place the apply path may block on the device: ktpu: hot
    def assignments(self) -> np.ndarray:
        if isinstance(self.handle, DeferredAssignments):
            return self.handle.get()
        return self.handle


@dataclass
class _StreamSlot:
    """One dispatched batch in the streaming dispatcher's bounded work
    ring (run_streaming): the prep — whose ``fence``/``occ_fence``
    captures are this slot's discard EPOCH, the per-stream-slot
    refinement of the global ``_conflict_seq``/``_occupancy_seq``
    discard windows — plus the slot's in-flight sub-solves. A
    conflicting event invalidates exactly the slots whose epoch
    predates it; slots chained on a discarded slot share its epoch (the
    chain is only ever extended inside one fence window), so the
    discard cascade is structural, never a separate bookkeeping pass.
    ``carried`` marks whether the dispatch left the session's stream
    carry resident for the next batch to chain on (nominated batches
    never do)."""

    prep: _PreparedGroup
    flights: list
    carried: bool


class Scheduler:
    # consecutive fence discards before run_pipelined falls back to one
    # synchronous (fence-free) cycle — the pipelined loop's livelock
    # backstop under sustained capacity/mask event churn (ADVICE r5 #2)
    _PIPELINE_FALLBACK_AFTER = 3

    def __init__(
        self,
        cluster: ClusterState,
        config: SchedulerConfig | None = None,
        clock: Clock | None = None,
    ):
        self.cluster = cluster
        self.config = config or SchedulerConfig()
        self.clock = clock or Clock()
        # span/batch id shared by the jax-profiler step annotation and
        # the obs span layer — initialized here instead of being
        # conjured via getattr at the call site, so profiler steps and
        # trace spans number identically
        self._trace_step = 0
        from .obs import build_obs

        # tracer (span layer), per-pod decision journal, flight
        # recorder — a disabled tracer and two Nones unless config.obs
        # turns them on
        self.obs, self.journal, self.flight = build_obs(
            self.config.obs, self.clock
        )
        # compile observability (obs/compile.py): the process-wide
        # XLA-compile watcher — dispatch brackets attribute compiles to
        # their shape scope; always on (it only costs work when a
        # compile already happened)
        from .obs.compile import WATCHER as _compile_watcher

        _compile_watcher.install()
        self._compile_watcher = _compile_watcher
        # live SLO engine (obs/slo.py): sliding-window p50/p99 pod
        # latency, bind throughput, multi-window error-budget burn —
        # ticked from _record_metrics off numbers the loops already
        # compute. None = off (the production default).
        self.slo = None
        if self.config.obs is not None and getattr(
            self.config.obs, "slo", None
        ) is not None:
            from .obs.slo import SloEngine

            self.slo = SloEngine(self.config.obs.slo, self.clock)
            self.slo.on_health_change.append(self._on_slo_health)
        # degraded-flag combiner: the fleet exchange's degraded flag is
        # the OR of the solve breaker's state and the SLO engine's
        # health — either signal routes handoff refugees elsewhere,
        # and neither may clear the flag while the other still holds it
        self._breaker_degraded = False
        self._slo_degraded = False
        # flight telemetry (obs/{profile,timeseries,sentinel,bundle}):
        # continuous per-stage profiler + anomaly sentinel + capture-
        # on-anomaly replay bundles, one coordinator ticked from the
        # commit seam. None = off (the production default) — the hot
        # path then pays a single attribute check per seam.
        from .obs import build_telemetry

        self.telemetry = build_telemetry(
            self.config.obs,
            self.clock,
            journal=self.journal,
            recorder=self.flight,
        )
        self._sentinel_degraded = False
        # high-volume span-family sampling state (see _on_event and
        # _commit_all): deterministic counters, first occurrence
        # always sampled
        self._enqueue_events = 0
        self._enqueue_sample_n = (
            max(int(self.config.obs.enqueue_span_sample_n), 1)
            if self.config.obs is not None
            else 1
        )
        self._bind_commits = 0
        self._bind_sample_n = (
            max(int(self.config.obs.bind_span_sample_n), 1)
            if self.config.obs is not None
            else 1
        )
        # fleet runtime (kubernetes_tpu/fleet): partition view, shard
        # watch filter, occupancy exchange client. Built before the
        # initial informer sync so the sync itself is shard-scoped.
        self.fleet = None
        self._span_tags: dict = {}
        if self.config.fleet is not None:
            from .fleet.runtime import FleetRuntime

            self.fleet = FleetRuntime(
                self.config.fleet, cluster, self.clock
            )
            # fleet-tagged observability: every journal record and the
            # per-batch root span carry the replica identity
            self._span_tags = {"replica": self.fleet.replica}
            if self.journal is not None:
                self.journal.tags["replica"] = self.fleet.replica
        if self.journal is not None:
            # journey-trace origin: the identity minted into each
            # pod's trace id at its FIRST record — replica-qualified in
            # fleet mode so a cross-replica trace names where the
            # journey started (the handoff row then ships it onward)
            self.journal.origin = (
                f"{self.fleet.replica if self.fleet is not None else 's'}"
                f"-{self.config.incarnation}"
            )
        if self.config.incarnation > 1:
            # restarted incarnations tag every record/span so a merged
            # cross-incarnation journal attributes each record to the
            # process that wrote it (first starts stay tag-free: their
            # journal bytes must not change under a config default)
            self._span_tags["incarnation"] = self.config.incarnation
            if self.journal is not None:
                self.journal.tags["incarnation"] = self.config.incarnation
        import logging

        self._log = logging.getLogger("kubernetes_tpu.scheduler")
        from .utils.featuregate import FeatureGates

        self.feature_gates = self.config.feature_gates or FeatureGates()
        self.cache = SchedulerCache(self.clock, assume_ttl=self.config.assume_ttl)
        # classify the flat out-of-tree plugin set by extension point
        from .framework.interface import Registry

        self.registry = Registry.classify(self.config.out_of_tree_plugins)

        def _pre_enqueue(pod: Pod) -> bool:
            for p in self.registry.pre_enqueue:
                if not p.pre_enqueue(pod).is_success:
                    return False
            return True

        qs = self.registry.queue_sort
        self.queue = PriorityQueue(
            self.clock,
            honor_scheduling_gates=self.feature_gates.enabled(
                "PodSchedulingReadiness"
            ),
            pre_enqueue=_pre_enqueue if self.registry.pre_enqueue else None,
            less=qs[0].less if qs else None,
        )
        # cached pending_pods gauge children: the gauge refreshes on
        # every queue transition (including per watch event), so the
        # label lookup must not be paid each time
        self._pending_gauges = {
            name: metrics.pending_pods.labels(name)
            for name in ("active", "backoff", "unschedulable", "gated")
        }
        # Permit WaitingPods map (runtime/waiting_pods_map.go): pod key ->
        # (WaitingPod, its QueuedPodInfo, scheduling cycle, CycleState,
        # pop timestamp). Verdicts recorded via WaitingPod.allow/reject
        # apply at the start of the next scheduling cycle.
        self._waiting: dict[str, tuple] = {}
        # outbound extender clients, configured order (extender.go)
        from .server.extender_client import HTTPExtenderClient

        self.extender_clients = tuple(
            HTTPExtenderClient(e) for e in self.config.extenders
        )
        # fold_out_of_tree memo (VERDICT r3 #8): signature -> (mask,
        # extra_score) outputs; LRU-capped at 8 like the class-table cache
        self._fold_cache: dict = {}
        # pods popped this cycle and not yet resolved: the unlocked solve
        # window means a MODIFIED watch event can arrive for a pod that is
        # neither queued nor waiting — without this map queue.update would
        # re-add it and double-schedule (review-caught)
        self._in_flight: dict[str, QueuedPodInfo] = {}  # ktpu: guarded-by(cluster.lock)
        # fence for the double-buffered loop (run_pipelined): bumped by any
        # watch event that could invalidate a dispatched-but-unapplied
        # solve (node capacity/mask changes, external pod placements). A
        # deferred solve whose fence no longer matches is discarded.
        self._conflict_seq = 0  # ktpu: guarded-by(cluster.lock)
        # occupancy fence for HARD-shaped deferred solves (ports/spread/
        # interpod/volumes/DRA/nominated): bumped by events that free or
        # re-key occupancy the shape's carried state cannot absorb —
        # assigned-pod deletes, assigned-pod label changes, external DRA
        # claim writes, nominator-map changes. Kept separate from
        # _conflict_seq so delete-churn never discards plain fit solves
        # (whose device carry absorbs frees conservatively).
        self._occupancy_seq = 0  # ktpu: guarded-by(cluster.lock)
        # the tuning layer's measurement surface (kubernetes_tpu/tuning):
        # ONE window of per-batch counter samples, which also owns the
        # RTT / per-pod-solve EWMAs the adaptive pipeline-split rule
        # reads (formerly private _rtt_ewma/_pod_solve_ewma — moved so
        # the split rule and the split controller can never fight over
        # the knob from two estimates). Always built: without a tuner
        # it costs one note_read per blocking flight, nothing per batch.
        from .tuning.window import CounterWindow

        self.window = CounterWindow(self.clock)
        # closed-loop auto-tuning runtime (SchedulerConfig.tuning):
        # per-knob hill-climb controllers ticked once per applied batch
        # from _record_metrics. None = static knobs.
        self.tuner = None
        if self.config.tuning is not None:
            from .tuning.runtime import TuningRuntime

            self.tuner = TuningRuntime(
                self.config.tuning, self.window, self.clock
            )
        # streaming dispatcher (run_streaming) infrastructure: the
        # completion thread + its handle queue are created lazily on the
        # first streaming cycle; the hidden/paid read tally feeds the
        # bench ladder's RTT attribution (driver thread only — a read is
        # "paid" when the driver actually blocked on it > 1 ms, which is
        # deterministic under FakeClock: virtual reads never block).
        self._completion_thread = None
        self._completion_q = None
        self._streaming_active = False
        self._reads_hidden = 0
        self._reads_paid = 0
        # backlog drain (drain_backlog): while active, dispatch spans
        # and journal records carry the drain-chunk id (prep.step -
        # base) so `obs explain` attributes a pod to the chunk that
        # placed it. Driver thread only; _note_drain_chunk points the
        # journal tag at the chunk about to write records.
        self._backlog_drain_active = False
        self._drain_chunk_base = 0
        # reusable port-occupancy staging (tensorize/plugins.PortStaging):
        # consecutive tensorizes against an unchanged cache — exactly the
        # streaming burst window — skip the placed-pod port re-scan
        from .tensorize.plugins import PortStaging

        self._port_staging = PortStaging()
        # profiles whose deferred solve was discarded: that profile's
        # device session carried the discarded placements and must
        # re-upload from host truth before its next dispatch (done at
        # _dispatch_group once no other solve is in flight). A set, not
        # a bool: multi-profile configs pipeline too, and healing the
        # WRONG profile's session would leave the polluted carry live.
        self._session_stale = set()  # ktpu: guarded-by(cluster.lock)
        # consecutive fence discards with no successful apply (driver
        # thread only — never touched by watch ingest): once it reaches
        # _PIPELINE_FALLBACK_AFTER, run_pipelined falls back to one
        # synchronous cycle so sustained event churn cannot livelock the
        # pipelined loop (ADVICE r5 #2). The streak counts PREPS, not
        # sub-flights: one event discarding a whole K-sub-batch chain is
        # ONE conflicting window, and counting it K times would engage
        # the fence-free backstop off a single isolated event
        # (review-caught); _last_discard_step dedupes within a chain —
        # an int, not the prep itself, so a discarded batch's tensors
        # aren't pinned on this 1-vCPU host until the next apply.
        self._discard_streak = 0
        self._last_discard_step = -1
        # sim/fault-injection seam (kubernetes_tpu/sim): called with the
        # in-flight solve right after every dispatch, while NO lock is
        # held — the one real boundary where a concurrent actor's watch
        # events can land between a solve's dispatch and its apply. The
        # simulator delivers delayed watch events here to exercise the
        # conflict fence and the livelock backstop deterministically.
        self._post_dispatch_hook = None
        # node-axis solve mesh (SchedulerConfig.mesh_devices): resolved
        # once — every dispatch (overlap/carry/sync, all profiles) runs
        # against it. None = single-device. The snapshot's node padding
        # is forced to a device-count multiple so the trailing node axis
        # always shards evenly; padded rows stay masked unschedulable.
        from .parallel.sharding import resolve_mesh

        self.mesh = resolve_mesh(
            self.config.mesh_devices, self.config.mesh_slice
        )
        self._mesh_devices = (
            int(self.mesh.size) if self.mesh is not None else 1
        )
        metrics.mesh_devices.set(self._mesh_devices)
        # fleet device-tier scale-out: the devices this replica's
        # EXCLUSIVE slice owns (0 = no slice configured)
        metrics.fleet_mesh_slice_devices.set(
            self._mesh_devices if self.config.mesh_slice is not None else 0
        )
        # degraded-mode solve resilience (kubernetes_tpu/resilience):
        # the fallback ladder + per-profile circuit breaker both
        # scheduling loops dispatch through, pre-apply output
        # validation, and the poison-batch quarantine. In fleet mode a
        # breaker trip publishes the replica's degraded flag through
        # the occupancy exchange so peers route refugees elsewhere.
        self.resilience = SolveResilience(
            self.config.resilience,
            self.clock,
            build_ladder(self.mesh is not None),
            # the combiner ORs the breaker's state with the SLO
            # engine's health before publishing the fleet degraded
            # flag (no-op without a fleet runtime)
            on_degraded=self._on_breaker_degraded,
        )
        # poison-batch quarantine: pod key -> (QueuedPodInfo, release
        # time). Entries re-admit through _release_quarantine at the
        # next pop once their TTL'd backoff elapses.
        self._quarantine: dict[str, tuple] = {}  # ktpu: guarded-by(cluster.lock)
        self._quarantine_counts: dict[str, int] = {}  # ktpu: guarded-by(cluster.lock)
        # gang scheduling (kubernetes_tpu/gang): assembly/retry tracker
        # plus the per-batch all-or-nothing round ledger. A round is
        # created when a complete gang enters a batch (gang id ->
        # {"expect": member keys, "done": resolved keys, "staged":
        # approved pending entries, "failed": bool, "reason": str}) and
        # resolves in _commit_all: every member staged -> ONE atomic
        # bind_gang commit; any member failed -> every staged placement
        # releases and the gang requeues (journal `gang_incomplete`).
        from .gang import GangTracker

        self._gang = (
            GangTracker(self.config.gang)
            if self.config.gang is not None
            else None
        )
        self._gang_rounds: dict[str, dict] = {}  # ktpu: guarded-by(cluster.lock)
        # ladder tier each profile last dispatched at: a tier change
        # moves the solve to different devices, so the resident session
        # must re-upload from host truth (driver thread only)
        self._tier_last: dict[str, str] = {}
        # sim/fault-injection seam (kubernetes_tpu/sim): called with
        # (pods, tier) right before every solve attempt at every ladder
        # tier — dispatch, probe, bisection sub-solve, host rung. May
        # raise to inject a solver-boundary fault deterministically.
        self._solve_fault = None
        # continuous rebalancer (kubernetes_tpu/rebalance): ticked by
        # both loops at idle cycle boundaries; None = off
        self.rebalancer = None
        if self.config.rebalance is not None:
            from .rebalance.runtime import Rebalancer

            self.rebalancer = Rebalancer(self.config.rebalance, self.clock)
        self.snapshot = Snapshot()
        self.snapshot.pad_multiple = self._mesh_devices
        from .state.volume_binder import VolumeBinder

        self.volume_binder = VolumeBinder(cluster)
        # dynamicresources plugin (behind the DynamicResourceAllocation
        # gate): the claim allocator is this framework's Reserve/PreBind
        # half; the filter half folds DraContext masks into the static
        # tables per batch
        from .state.claim_allocator import ClaimAllocator

        self.claim_allocator = ClaimAllocator(cluster)
        self._dra = self.feature_gates.enabled("DynamicResourceAllocation")
        # profile map: schedulerName -> solver (profile/profile.go#NewMap)
        from .api.objects import DEFAULT_SCHEDULER_NAME

        profile_cfgs = self.config.profiles or {
            DEFAULT_SCHEDULER_NAME: self.config.solver
        }
        self.solvers = {
            name: ExactSolver(cfg) for name, cfg in profile_cfgs.items()
        }
        self.solver = next(iter(self.solvers.values()))
        if self.telemetry is not None and self.telemetry.bundles is not None:
            # telemetry input-snapshot hook: every profile solver hands
            # its resolved solve inputs to the bundle capturer (the
            # capturer only retains them for batches the scheduler
            # armed, so host-tier/bisection solves don't capture)
            for s in self.solvers.values():
                s.capture_hook = self.telemetry.bundles.on_solve_input
        self.preemptor = PreemptionEvaluator()

        # nominated-pod index (the reference's nominator map): unbound pods
        # carrying status.nominatedNodeName, maintained from watch events so
        # the per-batch lookup is O(nominated), not O(all pods)
        self.nominated_pods: dict[str, Pod] = {}

        # commit fencing: the bind-path fence token for this incarnation
        # (state/cluster.py fencing tokens). Fleet replicas fence under
        # their per-shard lease identity by default, so a replica whose
        # lease a peer observed stale is fenced the moment the peer
        # commits the membership change at the state service.
        self._fence_role = self.config.fence_role
        if self._fence_role is None and self.fleet is not None:
            self._fence_role = self.fleet.lease_name
        self._fence_token = 0
        self._fenced_commits = 0  # ktpu: guarded-by(cluster.lock)
        # sim seam: called with the approved pending list right before
        # the binding cycle of a batch commits — the "after assume,
        # before bind" point a crash-restart drive kills the process at
        self._pre_commit_hook = None
        # the cold-start recovery pass: initial informer sync
        # (WaitForCacheSync equivalent) — atomic with the subscription
        # so a concurrent writer can't slip an object between the list
        # and the watch start — plus, on a RESTART (incarnation > 1),
        # orphan re-adoption, half-committed occupancy rollback, and
        # terminal `recovered` journaling. One root span + one
        # structured log line + scheduler_restart_recovery_seconds.
        self._recover()

    def _recover(self) -> None:
        """Cold-start recovery: rebuild every piece of incarnation-local
        scheduler state from ``ClusterState`` truth.

        All starts: shard-scoped cache/queue/nominator sync + watch
        subscription + (fleet) inventory/row publication, exactly the
        WaitForCacheSync contract.

        Restarts (``config.incarnation > 1``) additionally treat truth
        as a predecessor's wreck:

        - every unbound routed pod is RE-ADOPTED and terminally
          journaled ``recovered`` — a pod the dead incarnation left
          mid-flight (assumed, Permit-parked, popped, deferred-solved)
          has a dangling non-terminal journal history that no process
          will ever continue; the recovered record closes it so journal
          completeness holds across incarnations;
        - half-committed occupancy rolls back: resource-claim
          reservations naming unbound routed pods (a crash between the
          PreBind claim write and the bind commit) are released exactly
          like the deallocating controller would on pod delete, and a
          fleet replica's exchange rows are rebuilt wholesale from
          truth (a predecessor's stale PENDING rows would distort
          peers' admission forever);
        - quarantine and breaker state deliberately RESET rather than
          re-derive: both guard against *this process's* observed
          hardware/data failures, the restart may be on healed hardware
          or a fixed build, and the cost of being wrong is one cheap
          re-discovery (a poison pod re-quarantines via bisection in
          its first batch — tested in tests/test_restart_recovery.py),
          while persisting them would let a stale breaker pin a healthy
          scheduler to its degraded ladder rung indefinitely.
        """
        cluster = self.cluster
        restart = self.config.incarnation > 1
        t_rec = self.clock.perf()
        adopted = recovered = claims_rolled = 0
        span_tags = dict(self._span_tags)
        span_tags.setdefault("incarnation", self.config.incarnation)
        with cluster.lock, self.obs.span(
            "recover", trace_id=self._trace_step, restart=restart,
            **span_tags,
        ) as rsp:
            if self._fence_role is not None:
                self._fence_token = cluster.grant_fence(
                    self._fence_role,
                    holder=f"incarnation-{self.config.incarnation}",
                )
            for node in cluster.list_nodes():
                if self.fleet is None or self.fleet.owns_node(node.name):
                    self.cache.add_node(node)
            gangs_rolled = 0
            if restart and self._gang is not None:
                # half-staged gang rollback BEFORE pod adoption: a crash
                # between a gang's member binds (or between a fleet
                # stage and the gang commit) can leave a STRICT SUBSET
                # of a pod group bound — exactly the partial gang the
                # all-or-nothing contract forbids. Evict the stranded
                # members we own (delete+recreate collapses to unbound
                # under the same identity), so the adoption loop below
                # re-queues them and the gang reassembles whole. Runs
                # before `subscribe`, so the eviction's DELETED/ADDED
                # pair reaches no one — adoption sees post-rollback
                # truth directly.
                gangs_rolled = self._rollback_partial_gangs()
            for pod in cluster.list_pods():
                if pod.node_name:
                    if self.fleet is None or self.fleet.owns_node(
                        pod.node_name
                    ):
                        self.cache.add_pod(pod)
                else:
                    if self.fleet is not None and not self.fleet.routes_pod(
                        pod.key, pod
                    ):
                        continue
                    if pod.nominated_node_name:
                        self.nominated_pods[pod.key] = pod
                    if pod.scheduler_name in self.solvers:
                        self.queue.add(pod)
                        adopted += 1
                        if restart:
                            recovered += 1
                            if self.journal is not None:
                                self.journal.record(
                                    self._trace_step, 0, pod, "recovered",
                                    reason=(
                                        "re-adopted by incarnation "
                                        f"{self.config.incarnation} after "
                                        "a crash orphaned the pod"
                                        + (
                                            "; orphaned nomination on "
                                            + pod.nominated_node_name
                                            if pod.nominated_node_name
                                            else ""
                                        )
                                    ),
                                )
            if restart and self._dra:
                claims_rolled = self._rollback_orphan_claims()
            cluster.subscribe(
                self._on_event,
                filter=self.fleet.event_filter
                if self.fleet is not None
                else None,
            )
            if self.fleet is not None:
                self.fleet.publish_inventory()
                # rebuild this replica's exchange rows from truth: a
                # prior incarnation's stale PENDING rows (assumed but
                # never bound) roll back here, wholesale
                self.fleet.rebuild_pod_rows(self.cache)
                metrics.fleet_owned_nodes.set(len(self.cache.nodes))
            rsp.set(
                adopted=adopted, recovered=recovered,
                claims_rolled_back=claims_rolled,
                gangs_rolled_back=gangs_rolled,
            )
        dt = self.clock.perf() - t_rec
        metrics.restart_recovery_seconds.observe(dt)
        self._log.info(
            "recovery pass complete: incarnation %d %s %d pod(s), "
            "journaled %d recovered record(s), rolled back %d "
            "half-committed claim reservation(s) in %.3fs",
            self.config.incarnation,
            "re-adopted" if restart else "adopted",
            adopted, recovered, claims_rolled, dt,
            extra={"step": self._trace_step},
        )

    # runs inside _recover's locked region: ktpu: holds(cluster.lock)
    def _rollback_orphan_claims(self) -> int:
        """Release resource-claim reservations naming unbound pods this
        scheduler routes: only a crash between the PreBind claim write
        (``bind_pod_claims``) and the bind commit can produce one, so
        the reservation is half-committed occupancy — roll it back the
        way the deallocating controller would on pod delete. Pods this
        scheduler does not own are never touched: fleet PEERS' routed
        pods (a live peer may be mid-bind on them right now) and pods
        of FOREIGN schedulers (``spec.schedulerName`` outside our
        profiles — their scheduler may be between its own PreBind
        claim write and bind this instant)."""
        rolled = 0
        for c in list(self.cluster.list_resource_claims()):
            if not c.reserved_for:
                continue
            stale = []
            for key in c.reserved_for:
                ns, name = key.split("/", 1)
                try:
                    pod = self.cluster.get_pod(ns, name)
                except ApiError:
                    stale.append(key)  # reserved for a deleted pod
                    continue
                if pod.node_name:
                    continue  # bound: the reservation is legitimate
                if pod.scheduler_name not in self.solvers:
                    continue  # a foreign scheduler's pod: not ours
                if self.fleet is not None and not self.fleet.routes_pod(
                    key, pod
                ):
                    continue  # a peer's pod: leave it alone
                stale.append(key)
            if not stale:
                continue
            c.reserved_for = tuple(
                k for k in c.reserved_for if k not in stale
            )
            if not c.reserved_for:
                c.allocated_node = ""
                c.results = ()
            self.cluster.update_resource_claim(c)
            rolled += 1
        return rolled

    # runs inside _recover's locked region: ktpu: holds(cluster.lock)
    def _rollback_partial_gangs(self) -> int:
        """Restart-only: find pod groups where 0 < bound members <
        min-member — a predecessor crashed mid-gang (between member
        binds, or between a fleet stage and the atomic commit) — and
        evict the stranded bound members this scheduler owns, so the
        whole gang returns to Pending and reassembles atomically.
        Members on peer-owned nodes are left alone (the peer's own
        restart pass rolls its shard back); PDB-gated evictions (429)
        are tolerated per pod — the gang then completes on a later
        pass rather than losing protected members."""
        from .gang import GangTracker

        groups: dict[str, list] = {}
        for pod in self.cluster.list_pods():
            gid = GangTracker.gang_of(pod)
            if gid is not None:
                groups.setdefault(gid, []).append(pod)
        rolled = 0
        for gid in sorted(groups):
            members = groups[gid]
            bound = [p for p in members if p.node_name]
            if not bound:
                continue
            need = max(GangTracker.min_member(p) for p in members)
            if len(bound) >= need:
                continue  # complete (or over-satisfied): legitimate
            evicted = 0
            for p in bound:
                if self.fleet is not None and not self.fleet.owns_node(
                    p.node_name
                ):
                    continue
                try:
                    self.cluster.evict(
                        p.namespace,
                        p.name,
                        fence=(self._fence_role, self._fence_token)
                        if self._fence_role is not None
                        else None,
                    )
                    evicted += 1
                except ApiError as e:
                    self._log.warning(
                        "gang rollback: could not evict stranded "
                        "member %s of %s: %s", p.key, gid, e,
                    )
            if evicted:
                rolled += 1
                metrics.gang_incomplete_total.inc()
                self._log.info(
                    "gang rollback: pod group %s had %d/%d members "
                    "bound at restart; evicted %d stranded member(s) "
                    "back to Pending", gid, len(bound), need, evicted,
                )
        return rolled

    # -- degraded-health combiner (breaker state OR SLO health) --

    def _on_breaker_degraded(self, degraded: bool) -> None:
        """SolveResilience transition hook: the first breaker trip /
        last re-close. Publishes through the combiner so an
        SLO-degraded replica stays flagged even while its breakers are
        closed."""
        self._breaker_degraded = degraded
        if degraded and self.telemetry is not None:
            # forensic capture at the trip: the batch that tripped the
            # breaker is the newest complete solve record
            self.telemetry.capture("breaker")
        self._publish_degraded()

    def _on_slo_health(self, healthy: bool) -> None:
        """SloEngine health-flip hook: the error budget started (or
        stopped) burning past the threshold. Feeds the resilience
        layer — a half-open breaker defers its top-tier probe while
        the SLO is already degraded — and the fleet degraded flag, so
        handoff chains route refugees to replicas that are actually
        meeting their SLOs."""
        self._slo_degraded = not healthy
        self.resilience.set_slo_degraded(not healthy)
        self._publish_degraded()

    def _publish_degraded(self) -> None:
        if self.fleet is not None:
            self.fleet.set_solver_degraded(
                self._breaker_degraded
                or self._slo_degraded
                or self._sentinel_degraded
            )

    def reacquire_fence(self) -> None:
        """Re-acquire this scheduler's commit fence after it was
        revoked (lease re-acquired after a partition healed / a stall
        ended). The zombie path back to legitimacy: a fresh token is
        granted at the state service AND the scheduler forces a full
        resync first — both in-flight solves (fence bump) and, in fleet
        mode, the shard view rebuild — so post-refence commits are
        computed from current truth, never the stale pre-fence view.
        Production wires this to lease re-acquisition; the sim's
        hub_partition drive calls it at heal time."""
        with self.cluster.lock:
            if self._fence_role is None:
                return
            self._fence_token = self.cluster.grant_fence(
                self._fence_role,
                holder=f"incarnation-{self.config.incarnation}",
            )
            self._conflict_seq += 1
            self._occupancy_seq += 1
            if self.fleet is not None:
                self.fleet._needs_resync = True
            self._log.info(
                "commit fence re-acquired for role %r (token %d); full "
                "resync forced before the next solve",
                self._fence_role, self._fence_token,
                extra={"step": self._trace_step},
            )

    # -- eventhandlers.go#addAllEventHandlers routing --

    # ClusterState fires watch callbacks under its lock (every public
    # mutator takes it before _emit), so this handler always holds it:
    # ktpu: holds(cluster.lock)
    def _on_event(self, ev: Event) -> None:
        if ev.kind == "Event":
            return  # the scheduler's own recorder output
        if self.obs.enabled:
            # deterministic 1-in-N sampling (ObsConfig.enqueue_span_
            # sample_n): the enqueue span is the one family whose
            # volume scales with the EVENT rate, and spanning every
            # event at sustained-stream scale blows the obs-overhead
            # budget. The first event always samples; the counter is
            # deterministic so same-seed sims stay byte-identical.
            self._enqueue_events += 1
            n = self._enqueue_sample_n
            if n <= 1 or self._enqueue_events % n == 1:
                with self.obs.span(
                    "enqueue", kind=ev.kind, type=ev.type,
                    **({"sample_n": n} if n > 1 else {}),
                ):
                    self._ingest_event(ev)
            else:
                self._ingest_event(ev)
        else:
            self._ingest_event(ev)
        # any non-Event kind can have moved pods between queues: keep
        # the pending_pods gauge current (it used to refresh only in
        # the solve-recording path and went stale between solves)
        self._refresh_pending_gauge()

    # ktpu: holds(cluster.lock)
    def _ingest_event(self, ev: Event) -> None:
        if ev.kind in ("ResourceSlice", "DeviceClass", "ResourceClaim"):
            # DRA inventory/claim changes can unblock claim-bearing pods
            # (eventhandlers.go registers the dynamicresources plugin's
            # cluster events [U]); the hint stays conservative (move all)
            # EXCEPT for this scheduler's own binding-side claim writes
            # (reservedFor/allocation appends for a pod that just bound
            # TAKE devices — they cannot unblock a parked pod, and waking
            # the whole unschedulable map per bind defeats backoff).
            # Unreserve rollbacks FREE devices and are not suppressed.
            if self._dra and not self.claim_allocator.writing:
                # an external writer changed claim/inventory state a
                # DRA-active deferred solve folded at tensorize time
                self._occupancy_seq += 1
                self.queue.move_all_to_active_or_backoff(ev.kind + ev.type)
            return
        if ev.kind == "Pod":
            pod = ev.obj
            # nominator-map maintenance: an unbound pod with a nomination is
            # indexed; binding or clearing the nomination drops it
            if ev.type != "DELETED" and not pod.node_name and pod.nominated_node_name:
                # nominated-load changes stay advisory (the reference's
                # best-effort nominator semantics): they do NOT bump the
                # occupancy fence — our own preemption nominations land
                # mid-apply and would self-discard the rest of a chain
                self.nominated_pods[pod.key] = pod
            else:
                self.nominated_pods.pop(pod.key, None)
            if ev.type == "ADDED":
                if pod.node_name:
                    # an externally placed pod consumes capacity a deferred
                    # solve did not see
                    self._conflict_seq += 1
                    self.cache.add_pod(pod)
                elif pod.scheduler_name in self.solvers:
                    self.queue.add(pod)
            elif ev.type == "MODIFIED":
                if pod.node_name:
                    if not self.cache.is_assumed(pod.key):
                        # external bind/update of an assigned pod (our own
                        # bind confirmations arrive while still assumed).
                        # Fence-bump only when the update changes what a
                        # deferred solve consumed — placement or resource
                        # footprint; status heartbeats and label/condition
                        # flaps on running pods must not discard solves
                        # (review-caught pipeline-degeneration hazard)
                        old = None
                        old_node = self.cache.pod_node(pod.key)
                        if old_node is not None:
                            ninfo = self.cache.nodes.get(old_node)
                            if ninfo is not None:
                                old = ninfo.pods.get(pod.key)
                        if (
                            old is None
                            or old.node_name != pod.node_name
                            or old.resource_request()
                            != pod.resource_request()
                        ):
                            self._conflict_seq += 1
                        if old is None or old.labels != pod.labels:
                            # a placed pod's labels re-key spread domain
                            # counts and interpod term matching: only
                            # occupancy-carrying solves care (plain fit
                            # solves must not discard on label flaps —
                            # the original pipeline-degeneration hazard)
                            self._occupancy_seq += 1
                        self.cache.update_pod(pod)
                        # a pod this scheduler still had queued was bound
                        # by someone else: drop it (upstream's filtering
                        # handler pair fires the unassigned handler's
                        # OnDelete when a pod becomes assigned)
                        self.queue.delete(pod.key)
                    else:
                        self.cache.add_pod(pod)
                elif pod.key in self._in_flight:
                    # popped and mid-cycle (the unlocked solve window):
                    # refresh the in-flight copy; re-adding to the queue
                    # would double-schedule
                    self._in_flight[pod.key].pod = pod
                elif pod.key in self._waiting:
                    # parked at Permit: the pod is in flight (assumed +
                    # reserved), NOT queued — re-adding it here would
                    # double-schedule it. Refresh BOTH in-flight copies
                    # (the WaitingPod for the eventual bind and the
                    # QueuedPodInfo a rejection/timeout would requeue) so
                    # neither path resurrects the stale spec.
                    entry = self._waiting[pod.key]
                    entry[0].pod = pod
                    entry[1].pod = pod
                elif pod.scheduler_name in self.solvers:
                    self.queue.update(pod)
            else:  # DELETED
                if self.journal is not None:
                    # a deleted pod's journey trace can never continue;
                    # drop the entry so open-history traces stay
                    # bounded by live pods
                    self.journal.pod_traces.pop(pod.key, None)
                if pod.node_name:
                    freed_node = pod.node_name
                    self.cache.remove_pod(pod.key)
                    if self.fleet is not None:
                        # drop this replica's occupancy row (no-op on
                        # the non-owning replicas that also saw the
                        # event — withdraw only pops own rows)
                        self.fleet.withdraw(pod.key)
                    # freed ports / spread counts / interpod terms: for
                    # the fit carry a free is conservative, but a spread
                    # count overstated in the MIN domain loosens other
                    # domains' quotas and a vanished affinity peer can
                    # wrongly admit a placement — occupancy-carrying
                    # solves in flight must discard
                    self._occupancy_seq += 1
                    # AssignedPodDelete frees resources on ONE node: wake
                    # only pods whose requests fit its new free capacity
                    self.queue.move_all_to_active_or_backoff(
                        "AssignedPodDelete",
                        worth=self._fit_hint(freed_node),
                    )
                else:
                    self.queue.delete(pod.key)
                    # a pod deleted while parked at Permit: roll back its
                    # reservation (next cycle would otherwise bind it)
                    entry = self._waiting.pop(pod.key, None)
                    if entry is not None:
                        wp, _info, _cycle, state, _t0, _step = entry
                        self._unreserve_all(state, wp.pod, wp.node_name)
                        # the rollback freed assumed occupancy a deferred
                        # hard-shape solve may have counted
                        self._occupancy_seq += 1
        else:  # Node
            if ev.type == "ADDED":
                # node add/remove remaps snapshot slots: any in-flight
                # deferred solve's assignment indices go stale
                self._conflict_seq += 1
                self.cache.add_node(ev.obj)
                self.queue.move_all_to_active_or_backoff(
                    "NodeAdd", worth=self._fit_hint(ev.obj.name)
                )
            elif ev.type == "MODIFIED":
                old = self.cache.nodes.get(ev.obj.name)
                old_node = old.node if old is not None else None
                self.cache.update_node(ev.obj)
                # queueing-hint precheck (eventhandlers.go
                # #nodeSchedulingPropertiesChange): only wake parked pods for
                # node changes that could make one schedulable
                if old_node is None or _node_change_could_help(old_node, ev.obj):
                    # the same changes invalidate a deferred solve's masks
                    # and capacity math (pure heartbeats do not)
                    self._conflict_seq += 1
                    # label/taint/unschedulable changes can unblock pods
                    # regardless of resources; a pure allocatable change
                    # only helps pods that now FIT this node
                    resource_only = old_node is not None and (
                        old_node.labels == ev.obj.labels
                        and old_node.taints == ev.obj.taints
                        and old_node.unschedulable == ev.obj.unschedulable
                    )
                    self.queue.move_all_to_active_or_backoff(
                        "NodeUpdate",
                        worth=self._fit_hint(ev.obj.name, old=old_node)
                        if resource_only
                        else None,
                    )
            else:
                self._conflict_seq += 1
                self.cache.remove_node(ev.obj.name)

    def _fit_hint(self, node_name: str, old=None):
        """isPodWorthRequeuing gate for fit-shaped events (NodeAdd, a pure
        allocatable NodeUpdate, AssignedPodDelete): the event changed ONE
        node's capacity, so a parked pod is worth requeuing only if its
        requests fit that node's new free capacity (noderesources/fit.go
        #isSchedulableAfterNodeChange). Requests that don't fit there
        cannot have been unblocked by this event. With ``old`` (the
        pre-update Node on a resource-only NodeUpdate) the hint also
        checks the DELTA direction: a pod that already fit the old
        allocatable was not unblocked by this change — e.g. a shrink that
        still fits wakes nothing (the reference's hint compares old and
        new node infos the same way). Other filters (taints, selectors)
        are NOT checked — failing them here could only cause a missed
        wakeup if they also changed, which routes through the worth=None
        path. Returns None (move everything) when the
        SchedulerQueueingHints feature gate is off."""
        if not self.feature_gates.enabled("SchedulerQueueingHints"):
            return None

        def worth(info) -> bool:
            ninfo = self.cache.nodes.get(node_name)
            if ninfo is None or ninfo.node is None:
                return True  # node vanished mid-event: stay conservative
            node = ninfo.node
            if node.unschedulable:
                return False
            if len(ninfo.pods) + 1 > node.allowed_pod_number:
                return False
            for r, v in info.pod.resource_request().items():
                if v <= 0 or r == "pods":
                    continue
                if ninfo.used.get(r, 0) + v > node.allocatable.get(r, 0):
                    return False
            if old is not None:
                # fits the new capacity — but did it fail the OLD one?
                fits_old = len(ninfo.pods) + 1 <= old.allowed_pod_number
                if fits_old:
                    for r, v in info.pod.resource_request().items():
                        if v <= 0 or r == "pods":
                            continue
                        if ninfo.used.get(r, 0) + v > old.allocatable.get(
                            r, 0
                        ):
                            fits_old = False
                            break
                if fits_old:
                    return False  # change could not have unblocked it
            return True

        return worth

    # -- the scheduling loop --

    def schedule_batch(self) -> BatchResult:
        """One batched scheduling cycle: K pops -> one solve per profile ->
        K bindings. With a single profile (the common case) this is exactly
        one device solve; with multiple, pods route by spec.schedulerName
        (schedule_one.go#frameworkForPod) and sub-batches solve in pop
        order.

        Lock discipline (schedule_one.go's schedulingCycle/bindingCycle
        decoupling, batched): the cluster RLock is held in three short
        phases — (1) waiting-pod settlement + pop, (2) per group:
        snapshot + tensorize, then again for assume/Reserve/Permit after
        the solve — and NOT across the device solve or the bind commits.
        Ingest threads and a same-process extender server can therefore
        take the lock while the device works or a bind crosses the wire.
        The assume/forget protocol fences every gap: assumed pods are in
        the cache before the lock drops, so any concurrent snapshot
        counts them, and a mid-solve cache mutation lands in the NEXT
        cycle's snapshot (the same staleness window the reference's
        binding goroutines accept)."""
        from .utils import tracing

        self._trace_step += 1
        step = self._trace_step
        if tracing.enabled():
            with tracing.step("schedule_batch", step):
                return self._cycle_observed(step)
        return self._cycle_observed(step)

    def _cycle_observed(self, step: int) -> BatchResult:
        """One cycle under the obs root span, with the flight recorder
        dumped if the cycle dies (the crash trigger). The span and the
        jax-profiler step annotation share the ``_trace_step`` id."""
        if not self.obs.enabled and self.flight is None:
            return self._schedule_cycle()
        try:
            with self.obs.span(
                "schedule_batch", trace_id=step, step=step,
                **self._span_tags,
            ) as sp:
                res = self._schedule_cycle()
                sp.set(
                    scheduled=len(res.scheduled),
                    unschedulable=len(res.unschedulable),
                    bind_failures=len(res.bind_failures),
                )
                return res
        except Exception:
            if self.flight is not None:
                path = self.flight.dump(trigger="crash")
                self._log.exception(
                    "scheduling cycle failed; flight recorder dump: %s",
                    path, extra={"step": step},
                )
            raise

    # every caller requeues inside its locked region (watch events must
    # not interleave with the bookkeeping): ktpu: holds(cluster.lock)
    def _requeue(self, info: QueuedPodInfo, cycle: int) -> None:
        """AddUnschedulableIfNotPresent + in-flight bookkeeping: once a
        pod re-enters the queue, watch events must route to queue.update
        again instead of the in-flight refresh."""
        self._in_flight.pop(info.key, None)
        self.queue.add_unschedulable(info, cycle)

    def _schedule_cycle(self) -> BatchResult:
        pending: list[tuple] = []
        res = BatchResult()
        if self.fleet is not None:
            # apply any pending partition change (membership or
            # ring move) before popping, so this cycle solves against
            # the current shard
            self.fleet.maybe_resync(self)
        if self.rebalancer is not None:
            # background defragmentation: a no-op unless the interval
            # elapsed AND the queues are idle. Evictions re-enter the
            # queue synchronously (the eviction's watch events land
            # under the cluster lock), so the pop below picks the
            # migrating pods up in this same cycle.
            self.rebalancer.maybe_run(self, res)
        t0 = self.clock.perf()
        with self.cluster.lock, self.obs.span("pop") as sp:
            # re-admit quarantined pods whose TTL'd backoff elapsed
            self._release_quarantine()
            # reap assumes whose bind confirmation never arrived
            self._reap_expired_assumes()
            # WaitOnPermit analog: settle WaitingPods whose verdict or
            # deadline arrived since the last cycle, before popping new
            # work
            if self._waiting:
                self._process_waiting(res, pending)
            # #flushUnschedulablePodsLeftover: the reference runs this on
            # a 30s timer goroutine; batching gives a natural tick — pods
            # parked longer than 5 min force back into rotation
            self.queue.flush_unschedulable_leftover()
            infos = self.queue.pop_batch(self.config.batch_size)
            for i in infos:
                self._in_flight[i.key] = i
            if self._gang is not None:
                # gang gate: complete pod groups enter the batch whole
                # (contiguous), incomplete ones park until assembled
                infos = self._gang_gate(infos, res)
            sp.set(pods=len(infos))
            # idle/empty cycles change the queues too (waiting
            # settlement, leftover flush, the pop itself)
            self._refresh_pending_gauge()
        return self._run_popped(infos, t0, res, pending)

    def _run_popped(
        self,
        infos: list[QueuedPodInfo],
        t0: float,
        res: BatchResult | None = None,
        pending: list | None = None,
    ) -> BatchResult:
        """The synchronous cycle body for an already-popped batch (the
        pipelined driver pops before it knows whether a batch can overlap
        a deferred solve; non-overlappable batches route here)."""
        res = BatchResult() if res is None else res
        pending = [] if pending is None else pending
        try:
            if infos:
                self._run_groups(infos, res, pending, t0)
                res.host_seconds = (
                    self.clock.perf() - t0 - res.solve_seconds
                )
                self._record_metrics(
                    res, len(infos),
                    # the tuning window's hard-shape fraction must not
                    # collapse just because hard batches ROUTED through
                    # the synchronous cycle (degraded mode, backstop) —
                    # that would read as a workload shift on an
                    # unchanged workload (review-caught). The pod scan
                    # only runs when a tuner is actually sampling.
                    occ_sensitive=(
                        self.tuner is not None
                        and not self._plain_batch(
                            [i.pod for i in infos]
                        )
                    ),
                )
        except Exception:
            # a mid-cycle outage (non-ignorable extender down, plugin
            # ERROR) surfaces to the caller, but must not strand work:
            # popped pods that were neither approved, parked, nor already
            # requeued go back to the queue with backoff, and approved
            # binds still commit (the finally below).
            self._requeue_unhandled(infos, pending, res)
            raise
        finally:
            self._commit_all(infos, pending, res)
            if self._gang is not None:
                # a member quarantined/bisected out of the batch never
                # resolves its round: release the leftovers so staged
                # siblings can't stay assumed across batches
                with self.cluster.lock:
                    if self._gang_rounds:
                        self._release_gang_rounds_for(
                            {i.key for i in infos},
                            "gang round unresolved at batch end", res,
                        )
            res.completed_at = self.clock.perf()
        return res

    def _requeue_unhandled(
        self, infos: list[QueuedPodInfo], pending: list, res: BatchResult
    ) -> None:
        """Backoff-requeue every popped pod a mid-cycle exception left
        neither approved, parked, nor already requeued (shared by the
        sync and pipelined failure paths)."""
        released: set = set()
        if self._gang is not None:
            # abort every gang round this batch touched FIRST: staged
            # members release (unreserve + requeue) here, so the loop
            # below must treat them as handled
            with self.cluster.lock:
                if self._gang_rounds:
                    released = self._release_gang_rounds_for(
                        {i.key for i in infos},
                        "batch aborted mid-cycle", res,
                    )
        handled = (
            {e[2].key for e in pending}
            | set(res.unschedulable)
            | {k for k, _ in res.bind_failures}
            | set(res.quarantined)
            | set(self._waiting)
            | released
        )
        with self.cluster.lock:
            base = self.queue.scheduling_cycle
            for info in infos:
                if info.key not in handled:
                    if self.fleet is not None and not self.fleet.routes_pod(
                        info.key, info.pod
                    ):
                        # handed off to a peer earlier in this batch:
                        # requeueing locally would double-track the pod
                        # (the peer claims it from the exchange)
                        self._in_flight.pop(info.key, None)
                        continue
                    self._requeue(info, base)
            self._refresh_pending_gauge()

    def _commit_all(
        self, infos: list[QueuedPodInfo], pending: list, res: BatchResult
    ) -> None:
        """The binding-cycle pass for a batch's approved pods, plus
        in-flight bookkeeping teardown for exactly this batch (the
        pipelined loop keeps other batches' in-flight entries live).
        Gang rounds resolve here first: a round whose every member
        staged commits atomically via _commit_gang below; a failed or
        short round releases every staged placement (the
        all-or-nothing contract)."""
        gang_ready: list = []
        if self._gang is not None:
            with self.cluster.lock:
                if self._gang_rounds:
                    gang_ready = self._resolve_gang_rounds(res)
        hook = self._pre_commit_hook
        hook_pending = pending
        if gang_ready:
            # the crash seam must see the gang's staged entries too:
            # killing the process here is exactly the "assumed + staged
            # but nothing committed" window the restart rollback covers
            hook_pending = pending + [
                e for _gid, rd in gang_ready for e in rd["staged"]
            ]
        if hook is not None and hook_pending:
            # sim seam: the batch has assumed + approved its pods but
            # committed nothing — the exact point a crash-restart drive
            # kills the process (sim/harness.py crash_restart)
            hook(hook_pending)
        first_err = None
        bind_wall = 0.0
        for entry in pending:
            tb = self.clock.perf()
            # bind spans are 1-in-N sampled (ObsConfig.bind_span_
            # sample_n; deterministic counter, first bind always
            # sampled): the journal below stays COMPLETE per pod — the
            # span only adds the commit's wall duration, which
            # sampling preserves statistically, and per-pod spans at
            # sustained-stream volume are what the obs-overhead
            # budget cannot afford
            self._bind_commits += 1
            bn = self._bind_sample_n
            span_ctx = (
                self.obs.span(
                    "bind", trace_id=entry[6], pod=entry[2].key,
                    node=entry[3],
                    **({"sample_n": bn} if bn > 1 else {}),
                )
                if bn <= 1 or self._bind_commits % bn == 1
                else _NOOP_SPAN
            )
            with span_ctx as bsp:
                try:
                    ok = self._commit_binding(entry, res)
                except Exception as e:  # a buggy PreBind/PostBind plugin
                    # must not strand the REST of the approved batch:
                    # roll this pod back, keep committing, re-raise last
                    ok = False
                    first_err = first_err or e
                    state, info, pod, node_name, cycle, _ts, step = entry
                    with self.cluster.lock:
                        self._unreserve_all(state, pod, node_name)
                        res.bind_failures.append((pod.key, repr(e)))
                        self._requeue(info, cycle)
                        if self.journal is not None:
                            self.journal.record(
                                step, cycle, pod, "bind_failure",
                                node=node_name, reason=repr(e),
                                attempts=info.attempts,
                            )
                bsp.set(ok=ok)
            bind_dur = self.clock.perf() - tb
            bind_wall += bind_dur
            metrics.framework_extension_point_duration_seconds.labels(
                "Bind", "Success" if ok else "Error", "all"
            ).observe(bind_dur)
        for gid, rd in gang_ready:
            # one atomic all-or-nothing commit per complete gang round
            try:
                self._commit_gang(gid, rd, res)
            except Exception as e:
                first_err = first_err or e
        # LOCK001 (pre-analyzer gap): these pops ran unlocked, racing the
        # watch handler's in-flight refresh (_on_event could KeyError-skip
        # or resurrect an entry mid-pop on the ingest thread)
        with self.cluster.lock:
            # members of still-unresolved gang rounds (a split batch:
            # siblings ride a later flight) stay under the in-flight
            # fence — tearing them down would let a watch event
            # re-enqueue a pod whose placement is still staged
            gang_live = {
                k
                for rd2 in self._gang_rounds.values()
                for k in rd2["expect"]
            } if self._gang_rounds else set()
            for info in infos:
                if info.key not in gang_live:
                    self._in_flight.pop(info.key, None)
            for entry in pending:
                self._in_flight.pop(entry[1].key, None)
            # bind failures above requeued pods with backoff
            self._refresh_pending_gauge()
        if self.slo is not None and (
            res.e2e_latencies or res.bind_failures or res.scheduled
        ):
            # live SLO engine tick: POST-commit (the e2e latencies land
            # at _commit_binding), one chokepoint for every dispatch
            # loop — sync, pipelined, streaming, drain. Host arithmetic
            # over numbers this batch already materialized; zero new
            # device syncs (the CounterWindow sampling discipline).
            self.slo.observe_batch(res)
        if self.telemetry is not None and (infos or pending):
            # flight-telemetry tick, same post-commit chokepoint as the
            # SLO engine: close the batch's stage ledger (the bind wall
            # just measured is the last stage) and, at window
            # boundaries, run the sentinel's regression rules. All
            # host arithmetic; anomalies journal + capture here.
            self.telemetry.add_stage("bind", bind_wall)
            self.telemetry.observe_batch(
                self, step=self._trace_step, pods=len(pending)
            )
            if self._sentinel_degraded != self.telemetry.degraded:
                self._sentinel_degraded = self.telemetry.degraded
                self._publish_degraded()
        if first_err is not None:
            raise first_err

    def _group_by_profile(
        self, infos: list
    ) -> list[tuple[str, list, list[int]]]:
        """Profile sub-batches in pop order
        (schedule_one.go#frameworkForPod routing): (profile, infos,
        cycle offsets) per group — shared by the synchronous and
        pipelined loops so their batch composition can never diverge.
        Single-profile configs skip the bucketing pass."""
        if len(self.solvers) == 1:
            only = next(iter(self.solvers))
            return [(only, infos, list(range(len(infos))))]
        by_profile: dict[str, list] = {}
        order: list[str] = []
        for off, info in enumerate(infos):
            name = info.pod.scheduler_name
            if name not in by_profile:
                by_profile[name] = []
                order.append(name)
            by_profile[name].append((off, info))
        return [
            (
                name,
                [i for _, i in by_profile[name]],
                [off for off, _ in by_profile[name]],
            )
            for name in order
        ]

    def _run_groups(
        self, infos: list, res: BatchResult, pending: list, t0: float
    ) -> None:
        base_cycle = self.queue.scheduling_cycle - len(infos)
        for name, group_infos, cycle_offsets in self._group_by_profile(
            infos
        ):
            self._solve_group(
                name, group_infos, cycle_offsets, base_cycle, res, t0,
                pending,
            )

    def _solve_group(
        self,
        profile: str,
        infos: list[QueuedPodInfo],
        cycle_offsets: list[int],
        base_cycle: int,
        res: BatchResult,
        t0: float,
        pending: list,
        _depth: int = 0,
    ) -> None:
        """One profile sub-batch, synchronously: tensorize -> fold ->
        dispatch (blocking read) -> validate -> apply. run_pipelined
        drives the same phases with a deferred read between dispatch
        and apply so the next batch's host work overlaps this one's
        tunnel RTT.

        This is also the RESILIENT path (kubernetes_tpu/resilience):
        every dispatch runs at the tier the fallback ladder currently
        allows. A solve failure (exception, read death, or pre-apply
        validation rejecting the output) triggers one device-session
        rebuild and a retry; a deterministic failure trips the tier's
        circuit breaker and the batch retries one rung lower, down to
        the pure-host serial greedy — so a sick device degrades
        throughput, never progress. A batch that fails even the host
        rung (or dies in tensorize, which no tier can fix) is
        data-shaped: it bisects to the offending pod(s), which are
        quarantined with a terminal journal outcome while the rest of
        the batch proceeds (``_bisect_or_quarantine``)."""
        solver = self.solvers[profile]
        try:
            prep = self._tensorize_group(
                profile, infos, cycle_offsets, base_cycle, t0
            )
        except Exception as e:
            # tensorize is tier-independent: no ladder rung can fix a
            # batch whose data breaks it — isolate the poison instead
            self._solver_failed(
                infos, e, "tensorize", self._trace_step, base_cycle
            )
            self._bisect_or_quarantine(
                profile, infos, cycle_offsets, base_cycle, res, t0,
                pending, e, _depth,
            )
            return
        with self.obs.span(
            "fold", trace_id=prep.step, profile=profile,
            extenders=len(self.extender_clients),
            plugins=len(self.config.out_of_tree_plugins),
        ):
            # extender/plugin folding keeps its own failure semantics
            # (a non-ignorable extender outage aborts the batch): NOT
            # wrapped by the ladder
            self._fold_group(prep)
        while True:
            tier_idx, tier = self.resilience.acquire(profile)
            act = err = None
            try:
                if tier == TIER_HOST:
                    flight = self._host_dispatch(prep)
                else:
                    flight = self._dispatch_group(
                        prep, defer=False, tier=tier
                    )
            except SessionDrainRequired:
                raise  # pipelined-protocol control flow, not a fault
            except Exception as e:
                err = e
                self._solver_failed(
                    infos, e, None, prep.step, base_cycle
                )
                act = self.resilience.on_failure(profile, tier_idx)
            else:
                try:
                    # pre-apply validation runs inside _apply_group
                    # BEFORE any mutation: a SolverFaultError here is a
                    # failed solve, retryable at a lower rung
                    self._apply_group(flight, res, pending)
                except SolverFaultError as e:
                    err = e
                    self._solver_failed(
                        infos, e, None, prep.step, base_cycle
                    )
                    act = self.resilience.on_failure(profile, tier_idx)
                else:
                    self.resilience.on_success(profile, tier_idx)
                    if tier != self.resilience.ladder[0]:
                        metrics.fallback_solves_total.labels(tier).inc()
                    return
            # breaker span + flight-recorder dump: the trip is the
            # moment worth a forensic snapshot (the ring still holds
            # the failing dispatch's spans/decisions)
            with self.obs.span(
                "breaker", trace_id=prep.step, profile=profile,
                tier=tier, action=act,
            ):
                pass
            if act == ACT_DESCEND and self.flight is not None:
                self.flight.dump(trigger="breaker")
            if act == ACT_REBUILD:
                solver.reset_session()
                continue
            if act != ACT_BISECT:
                continue  # retry / descend: re-acquire the tier
            # the last rung failed: data-shaped — isolate it
            self._bisect_or_quarantine(
                profile, infos, cycle_offsets, base_cycle, res, t0,
                pending, err, _depth,
            )
            return

    def _host_dispatch(self, prep: _PreparedGroup) -> _InFlightSolve:
        """The ladder's last rung: solve the prepared group with the
        pure-host serial greedy (resilience.host_greedy_assign) —
        zero accelerator surface, so device loss cannot take it down.
        Returns a flight shaped exactly like a device dispatch so the
        apply path downstream is identical."""
        solver = self.solvers[prep.profile]
        hook = self._solve_fault
        if hook is not None:
            hook(prep.pods, TIER_HOST)
        t1 = self.clock.perf()
        with self.cluster.lock:
            placed = self._placed_by_slot()
        with self.obs.span(
            "dispatch", trace_id=prep.step, profile=prep.profile,
            defer=False, tier=TIER_HOST,
        ):
            assignments = host_greedy_assign(
                prep, placed, solver.config
            )
        # the next device-tier dispatch must re-upload the session:
        # host-rung placements never touched the device carry
        self._tier_last[prep.profile] = TIER_HOST
        dispatch_dt = self.clock.perf() - t1
        if not prep.timing_observed:
            prep.timing_observed = True
            prep.tensorize_seconds = max(t1 - prep.gs, 0.0)
            metrics.tensorize_seconds.observe(prep.tensorize_seconds)
            metrics.framework_extension_point_duration_seconds.labels(
                "PreFilter", "Success", prep.profile
            ).observe(prep.tensorize_seconds)
        return _InFlightSolve(
            prep=prep, handle=assignments, dispatch_seconds=dispatch_dt
        )

    def _solver_failed(
        self,
        infos: list[QueuedPodInfo],
        exc: Exception,
        reason: str | None,
        step: int,
        base_cycle: int,
    ) -> None:
        """Journal + count a failed batched solve: a
        scheduler_batch_failure_total{reason} tick and a non-terminal
        ``solver_error`` journal record per pod, so `explain <pod>`
        shows the retry history instead of a silent requeue."""
        if reason is None:
            if isinstance(exc, SolveCorruptError):
                reason = "corrupt"
            elif isinstance(exc, SolverReadError):
                reason = "read"
            else:
                reason = "dispatch"
        metrics.batch_failure_total.labels(reason).inc()
        self._log.warning(
            "batched solve failed (%s, %d pods): %r",
            reason, len(infos), exc, extra={"step": step},
        )
        self._note_drain_chunk(step)
        if self.journal is not None:
            for info in infos:
                self.journal.record(
                    step, base_cycle, info.pod, "solver_error",
                    reason=f"{reason}: {exc!r}", attempts=info.attempts,
                )

    def _bisect_or_quarantine(
        self,
        profile: str,
        infos: list[QueuedPodInfo],
        cycle_offsets: list[int],
        base_cycle: int,
        res: BatchResult,
        t0: float,
        pending: list,
        exc: Exception,
        depth: int,
    ) -> None:
        """Poison-batch isolation: the batch failed every ladder rung
        (or tensorize itself), so the failure is data-dependent. Bisect
        to the offending pod(s): each half re-enters the resilient
        solve, halves without the poison proceed normally, and a
        singleton that still fails is quarantined with a terminal
        journal outcome and a TTL'd backoff re-admit.

        Gang members are an indivisible unit: bisection never splits
        THROUGH a pod group (the gate made gangs contiguous, so the
        midpoint just shifts to the nearest group boundary), and a
        slice reduced to one whole unsatisfiable gang quarantines the
        group as a unit instead of bisecting into it."""
        if self._gang is not None and infos:
            gids = [self._gang.gang_of(i.pod) for i in infos]
            if gids[0] is not None and all(g == gids[0] for g in gids):
                # the poison isolated to ONE whole gang: all-or-nothing
                # applies to quarantine too
                self._quarantine_gang(gids[0], infos, exc, res)
                return
        if len(infos) == 1:
            self._quarantine_pod(
                infos[0], base_cycle + cycle_offsets[0] + 1, exc, res
            )
            return
        mid = len(infos) // 2
        if self._gang is not None:
            # shift the split point off a gang's interior: prefer the
            # nearest boundary where the two neighbors are not members
            # of the same group (one exists — the all-same-gang case
            # returned above)
            def _boundary(b: int) -> bool:
                return not (
                    gids[b - 1] is not None and gids[b - 1] == gids[b]
                )

            if not _boundary(mid):
                for d in range(1, len(infos)):
                    if mid - d >= 1 and _boundary(mid - d):
                        mid = mid - d
                        break
                    if mid + d <= len(infos) - 1 and _boundary(mid + d):
                        mid = mid + d
                        break
        with self.obs.span(
            "bisect", trace_id=self._trace_step, profile=profile,
            pods=len(infos), depth=depth,
        ):
            for lo, hi in ((0, mid), (mid, len(infos))):
                self._solve_group(
                    profile, infos[lo:hi], cycle_offsets[lo:hi],
                    base_cycle, res, t0, pending, _depth=depth + 1,
                )

    def _quarantine_pod(
        self, info: QueuedPodInfo, cycle: int, exc: Exception,
        res: BatchResult,
    ) -> None:
        """Terminal quarantine for a pod whose presence deterministically
        breaks the solve: journaled ``quarantined`` with the exception,
        out of every queue, re-admitted after a TTL'd backoff
        (_release_quarantine)."""
        cfg = self.resilience.config
        pod = info.pod
        with self.cluster.lock:
            self._in_flight.pop(info.key, None)
            self.queue.delete(info.key)
            n = self._quarantine_counts.get(info.key, 0) + 1
            self._quarantine_counts[info.key] = n
            ttl = min(
                cfg.quarantine_ttl * cfg.quarantine_backoff ** (n - 1),
                cfg.max_quarantine_ttl,
            )
            self._quarantine[info.key] = (info, self.clock.now() + ttl)
            res.quarantined.append(info.key)
            metrics.quarantined_pods_total.inc()
            self._log.warning(
                "pod %s quarantined for %.0fs (quarantine #%d): solve "
                "failure isolated to this pod: %r",
                info.key, ttl, n, exc, extra={"step": self._trace_step},
            )
            self._event(
                pod, "FailedScheduling",
                f"quarantined: the batched solve fails whenever this "
                f"pod is included: {exc!r}", type_="Warning",
            )
            self._note_drain_chunk(self._trace_step)
            if self.journal is not None:
                self.journal.record(
                    self._trace_step, cycle, pod, "quarantined",
                    reason=repr(exc), attempts=info.attempts,
                )
            self._refresh_pending_gauge()

    # called from the locked pop regions of both loops: ktpu: holds(cluster.lock)
    def _release_quarantine(self) -> None:
        """Re-admit quarantined pods whose TTL'd backoff elapsed (the
        retry may succeed — the poison may have been a transient data
        interaction, a since-fixed webhook, or a healed tier). Pods
        deleted or bound while quarantined just drop out."""
        if not self._quarantine:
            return
        now = self.clock.now()
        for key in sorted(self._quarantine):
            info, release = self._quarantine[key]
            if release > now:
                continue
            del self._quarantine[key]
            try:
                ns, name = key.split("/", 1)
                cur = self.cluster.get_pod(ns, name)
            except ApiError:
                self._quarantine_counts.pop(key, None)
                continue  # deleted while quarantined
            if cur.node_name:
                self._quarantine_counts.pop(key, None)
                continue  # bound by someone else while quarantined
            info.pod = cur
            self.queue.requeue_popped(info)
            metrics.quarantine_readmits_total.inc()

    # called from the locked pop regions of both loops: ktpu: holds(cluster.lock)
    def _reap_expired_assumes(self) -> None:
        """Expire assumed pods whose bind confirmation never arrived
        (cache.cleanup_expired — finished assumes past their deadline,
        plus unfinished assumes a dead binding cycle leaked past the
        TTL; Permit-parked pods are protected). The release frees
        occupancy in-flight solves may have counted, so both fences
        bump; a pod still unbound in truth re-enters the queue, a pod
        actually bound (confirmation event lost) re-adopts from
        truth."""
        expired = self.cache.cleanup_expired(
            protected=frozenset(self._waiting)
        )
        if not expired:
            return
        self._conflict_seq += 1
        self._occupancy_seq += 1
        for key in expired:
            self._log.warning(
                "assumed pod %s expired without a bind confirmation; "
                "occupancy released", key,
                extra={"step": self._trace_step},
            )
            ns, name = key.split("/", 1)
            try:
                cur = self.cluster.get_pod(ns, name)
            except ApiError:
                # deleted: drop the leaked host-side reservations too
                if self.fleet is not None:
                    self.fleet.withdraw(key)
                self.volume_binder.unreserve(key)
                self.claim_allocator.unreserve(key)
                continue
            if cur.node_name:
                # the bind actually landed and only the confirmation
                # event was lost: re-adopt real occupancy from truth.
                # The exchange row stays — it was COMMITTED at bind
                # time and still represents durable occupancy peers
                # must respect (withdrawing it here would hide a bound
                # pod from cross-shard admission; review-caught)
                self.cache.add_pod(cur)
                continue
            if self.fleet is not None:
                self.fleet.withdraw(key)
            self.volume_binder.unreserve(key)
            self.claim_allocator.unreserve(key)
            if (
                key not in self.queue.entries()
                and key not in self._in_flight
                and key not in self._quarantine
                and cur.scheduler_name in self.solvers
                and (
                    self.fleet is None
                    or self.fleet.routes_pod(key, cur)
                )
            ):
                self.queue.add(cur)
        self._refresh_pending_gauge()

    def _requeue_immediate(self, infos: list[QueuedPodInfo]) -> None:
        """Requeue a batch whose deferred dispatch failed before any
        flight existed: head of the active queue, no backoff (the
        failure is the solve's, not the pods') — the retry routes
        through the synchronous resilient path. Externally bound or
        deleted pods drop out (mirrors _discard_flight)."""
        with self.cluster.lock:
            if self._gang is not None and self._gang_rounds:
                self._release_gang_rounds_for(
                    {i.key for i in infos},
                    "gang member's dispatch failed before any flight",
                )
            for info in infos:
                self._in_flight.pop(info.key, None)
                try:
                    cur = self.cluster.get_pod(
                        info.pod.namespace, info.pod.name
                    )
                except ApiError:
                    continue
                if cur.node_name:
                    continue
                info.pod = cur
                self.queue.requeue_popped(info)
            self._refresh_pending_gauge()

    # -- gang scheduling (kubernetes_tpu/gang): all-or-nothing pod
    # groups. The gate assembles groups at pop time, _apply_group
    # STAGES members instead of queueing them for individual commit,
    # and _commit_all resolves each round — one atomic bind_gang when
    # every member staged, a full release + requeue otherwise. --

    # called from the locked pop regions of all three loops:
    # ktpu: holds(cluster.lock)
    def _gang_gate(
        self, infos: list, res: BatchResult | None = None
    ) -> list:
        """Rewrite a popped batch so pod groups enter it whole or not
        at all: pull a ready gang's remaining members straight out of
        the queue (any heap position, any backoff state), park an
        incomplete gang's members back as unschedulable (journal
        ``gang_incomplete``) until the group assembles or times out,
        and quarantine a gang that timed out or exhausted its
        all-or-nothing retries. Ready gangs re-enter the batch as
        CONTIGUOUS runs — the bisection boundary alignment depends on
        it — after the non-gang pods, which keep pop order."""
        tracker = self._gang
        if tracker is None:
            return infos
        groups: dict[str, list] = {}
        out: list = []
        for info in infos:
            gid = tracker.gang_of(info.pod)
            if gid is None:
                out.append(info)
            else:
                groups.setdefault(gid, []).append(info)
        if not groups:
            return infos
        from .gang import GangUnsatisfiableError

        popped_keys = {i.key for i in infos}
        now = self.clock.now()
        cfg = tracker.config
        for gid in sorted(groups):
            members = groups[gid]
            taken = self.queue.take_for_gang(
                lambda p, _g=gid: tracker.gang_of(p) == _g,
                exclude=popped_keys,
            )
            for t in taken:
                self._in_flight[t.key] = t
            members = members + taken
            need = max(tracker.min_member(m.pod) for m in members)
            first = tracker.note_seen(gid, now)
            if len(members) >= need:
                rounds = tracker.incomplete_rounds(gid)
                if rounds >= cfg.quarantine_after:
                    self._quarantine_gang(
                        gid, members,
                        GangUnsatisfiableError(
                            f"pod group {gid} failed its all-or-"
                            f"nothing round {rounds} consecutive "
                            "times"
                        ),
                        res,
                    )
                    continue
                self._gang_rounds[gid] = {
                    "expect": {m.key for m in members},
                    "done": set(),
                    "staged": [],
                    "failed": False,
                    "reason": "",
                }
                out.extend(members)
                continue
            if now - first > cfg.min_member_timeout:
                self._quarantine_gang(
                    gid, members,
                    GangUnsatisfiableError(
                        f"pod group {gid} assembled only "
                        f"{len(members)}/{need} members within "
                        f"{cfg.min_member_timeout:.0f}s"
                    ),
                    res,
                )
                continue
            # incomplete and still inside the assembly window: park
            # every present member as unschedulable — NOT requeue_popped,
            # which would re-pop the same partial group every cycle in a
            # busy loop. A later member's pop (or the leftover flush)
            # brings them back through take_for_gang above.
            cycle = self.queue.scheduling_cycle
            for m in members:
                self._requeue(m, cycle)
                if self.journal is not None:
                    self.journal.record(
                        self._trace_step, cycle, m.pod,
                        "gang_incomplete",
                        reason=(
                            f"waiting for pod group {gid}: "
                            f"{len(members)}/{need} members present"
                        ),
                        attempts=m.attempts,
                    )
        return out

    # ktpu: holds(cluster.lock) — called from _apply_group's locked region
    def _gang_round_of(self, pod: Pod) -> dict | None:
        """The live all-or-nothing round this pod belongs to, if any."""
        if self._gang is None or not self._gang_rounds:
            return None
        gid = self._gang.gang_of(pod)
        if gid is None:
            return None
        rd = self._gang_rounds.get(gid)
        if rd is not None and pod.key in rd["expect"]:
            return rd
        return None

    # ktpu: holds(cluster.lock) — called from _apply_group's locked region
    def _gang_note_fail(self, rd: dict | None, pod: Pod, reason: str) -> None:
        """Mark a gang member's attempt resolved-as-failed: the round
        can never commit, and _commit_all releases every staged
        sibling once all members have resolved."""
        if rd is None:
            return
        rd["done"].add(pod.key)
        rd["failed"] = True
        if not rd["reason"]:
            rd["reason"] = f"member {pod.key} failed: {reason}"

    # ktpu: holds(cluster.lock)
    def _resolve_gang_rounds(self, res: BatchResult) -> list:
        """Sweep rounds whose every member has resolved: a clean round
        (all staged) moves to the atomic-commit list; a failed or
        short round releases every staged placement and the gang
        requeues whole. Returns [(gid, round)] ready to commit."""
        ready: list = []
        for gid in sorted(self._gang_rounds):
            rd = self._gang_rounds[gid]
            if not rd["expect"] <= rd["done"]:
                continue  # members still unresolved (a later flight)
            del self._gang_rounds[gid]
            if rd["failed"] or len(rd["staged"]) < len(rd["expect"]):
                self._release_gang_round(
                    gid, rd, res,
                    rd["reason"] or "not every member could be placed",
                )
            else:
                ready.append((gid, rd))
        return ready

    # ktpu: holds(cluster.lock)
    def _release_gang_round(
        self, gid: str, rd: dict, res: BatchResult | None, reason: str
    ) -> set:
        """All-or-nothing rollback: unreserve every STAGED member's
        placement (assume, volumes, claims, fleet row — the same
        rollback every individual failure path uses) and requeue it
        with backoff; journal ``gang_incomplete`` per released member.
        A partial gang is never left bound — this is the release half
        of the atomicity contract."""
        released: set = set()
        for entry in rd["staged"]:
            state, info, pod, node_name, cycle, _t0, step = entry
            self._unreserve_all(state, pod, node_name)
            self._requeue(info, cycle)
            released.add(pod.key)
            if res is not None:
                res.gang_released.append(pod.key)
            if self.journal is not None:
                self.journal.record(
                    step, cycle, pod, "gang_incomplete",
                    node=node_name, reason=reason,
                    attempts=info.attempts,
                )
        metrics.gang_incomplete_total.inc()
        if self._gang is not None:
            self._gang.note_incomplete(gid)
        self._log.info(
            "pod group %s round released (%d staged placement(s) "
            "rolled back): %s", gid, len(released), reason,
            extra={"step": self._trace_step},
        )
        self._refresh_pending_gauge()
        return released

    # ktpu: holds(cluster.lock)
    def _release_gang_rounds_for(
        self, keys: set, reason: str, res: BatchResult | None = None
    ) -> set:
        """Force-resolve every live round touching ``keys`` (a
        discarded flight, an aborted batch, a quarantined member):
        the round can no longer complete, so its staged placements
        release and the gang requeues whole."""
        released: set = set()
        if not self._gang_rounds:
            return released
        for gid in sorted(self._gang_rounds):
            rd = self._gang_rounds[gid]
            if not (rd["expect"] & keys):
                continue
            del self._gang_rounds[gid]
            released |= self._release_gang_round(gid, rd, res, reason)
        return released

    def _quarantine_gang(
        self, gid: str, members: list, exc: Exception,
        res: BatchResult | None,
    ) -> None:
        """Quarantine a WHOLE pod group — bisection never splits
        through a gang, and an unsatisfiable gang (min-member timeout,
        exhausted all-or-nothing retries) leaves the queue as a unit.
        Members re-admit together after the TTL'd backoff
        (_release_quarantine), and the gate reassembles them."""
        res = BatchResult() if res is None else res
        with self.cluster.lock:
            rd = self._gang_rounds.pop(gid, None)
            if rd is not None and rd["staged"]:
                self._release_gang_round(
                    gid, rd, res, f"gang quarantined: {exc!r}"
                )
        for m in members:
            self._quarantine_pod(
                m, self.queue.scheduling_cycle, exc, res
            )
        metrics.gang_quarantined_total.inc()
        if self._gang is not None:
            self._gang.note_quarantined(gid)
        if self.telemetry is not None:
            # forensic capture: the batch whose solve failure
            # quarantined the gang is the newest complete record
            self.telemetry.capture("quarantine", note=f"gang {gid}: {exc!r}")
        self._log.warning(
            "pod group %s quarantined whole (%d member(s)): %r",
            gid, len(members), exc, extra={"step": self._trace_step},
        )

    def _commit_gang(self, gid: str, rd: dict, res: BatchResult) -> None:
        """The atomic binding cycle for one complete gang round:
        per-member PreBind (plugins, volumes, DRA claims), then ONE
        all-or-nothing ``ClusterState.bind_gang`` commit under this
        incarnation's fence. Any failure — a PreBind rejection, a
        fence revocation, a member bound externally mid-flight —
        releases EVERY member's placement and the gang requeues whole:
        zero partial binds, by construction. Runs without the cluster
        lock held (the commit may cross a wire), like
        _commit_binding."""
        entries = rd["staged"]
        step = entries[0][6] if entries else self._trace_step
        with self.obs.span(
            "bind_gang", trace_id=step, gang=gid, pods=len(entries),
        ) as gsp:
            try:
                for entry in entries:
                    state, info, pod, node_name, cycle, _t0, _s = entry
                    for p in self.registry.pre_bind:
                        st = p.pre_bind(state, pod, node_name)
                        if not st.is_success:
                            raise _Rejected(
                                f"PreBind plugin {p.name()} rejected "
                                f"{pod.key}: " + "; ".join(st.reasons)
                            )
                    if pod.pvc_names:
                        self.volume_binder.bind_pod_volumes(pod)
                    if self._dra and pod.resource_claim_names:
                        self.claim_allocator.bind_pod_claims(pod)
                self.cluster.bind_gang(
                    [
                        (e[2].namespace, e[2].name, e[3])
                        for e in entries
                    ],
                    fence=(
                        (self._fence_role, self._fence_token)
                        if self._fence_role is not None
                        else None
                    ),
                )
            except (
                ApiError, VolumeBindingError, _Rejected, ExtenderError,
            ) as e:
                reason = e.reason if isinstance(e, ApiError) else str(e)
                fenced = isinstance(e, ApiError) and e.fenced
                gsp.set(ok=False, reason=reason)
                with self.cluster.lock:
                    if fenced:
                        metrics.commit_fenced_total.inc()
                        self._fenced_commits += 1
                        self._log.warning(
                            "gang bind of %s fenced: this "
                            "incarnation's commit fence (role %r) was "
                            "revoked — no member bound",
                            gid, self._fence_role,
                            extra={"step": step},
                        )
                    self._release_gang_round(
                        gid, rd, res, f"gang bind failed: {reason}"
                    )
                return
            gsp.set(ok=True)
        now_perf = self.clock.perf()
        with self.cluster.lock:
            for entry in entries:
                state, info, pod, node_name, cycle, _t0, estep = entry
                self.cache.finish_binding(pod.key)
                self.volume_binder.finish(pod.key)
                self.claim_allocator.finish(pod.key)
                if self.fleet is not None:
                    self.fleet.commit(pod.key)
                self._event(
                    pod, "Scheduled",
                    f"Successfully assigned {pod.key} to {node_name} "
                    f"(pod group {gid}, all {len(entries)} members "
                    "bound atomically)",
                    action="Binding",
                )
                res.scheduled.append((pod.key, node_name))
                if self.journal is not None:
                    self.journal.record(
                        estep, cycle, pod, "bound",
                        node=node_name, attempts=info.attempts,
                    )
                self._in_flight.pop(pod.key, None)
            self._refresh_pending_gauge()
        for entry in entries:
            state, info, pod, node_name, _cycle, t_start, _s = entry
            res.latencies.append(now_perf - t_start)
            e2e = max(
                self.clock.now() - info.initial_attempt_timestamp, 0.0
            )
            res.e2e_latencies.append(e2e)
            metrics.pod_scheduling_attempts.observe(info.attempts)
            metrics.pod_scheduling_sli_duration_seconds.labels(
                str(min(info.attempts, 16))
            ).observe(e2e)
            for p in self.registry.post_bind:
                p.post_bind(state, pod, node_name)
        metrics.gang_commits_total.inc()
        metrics.gang_bound_pods_total.inc(len(entries))
        first = self._gang.note_complete(gid) if self._gang else None
        if first is not None:
            metrics.gang_assembly_seconds.observe(
                max(self.clock.now() - first, 0.0)
            )

    def _tensorize_group(
        self,
        profile: str,
        infos: list[QueuedPodInfo],
        cycle_offsets: list[int],
        base_cycle: int,
        t0: float,
    ) -> _PreparedGroup:
        """Phase 2a (locked): snapshot + tensorize against a consistent
        view of cache + cluster."""
        solver = self.solvers[profile]
        gs = self.clock.perf()
        with self.cluster.lock, self.obs.span(
            # explicit trace id: the pipelined loop has no root span, so
            # parent inheritance alone would leave these spans on trace 0
            "tensorize", trace_id=self._trace_step,
            profile=profile, pods=len(infos),
        ) as tsp:
            # phase 2a: snapshot + tensorize against a consistent view
            with self.obs.span("snapshot"):
                batch = self.snapshot.update(self.cache)
            tsp.set(nodes=batch.num_nodes, fence=self._conflict_seq)
            pods = [i.pod for i in infos]

            def has_pod_affinity(p: Pod) -> bool:
                return p.affinity is not None and (
                    p.affinity.pod_affinity is not None
                    or p.affinity.pod_anti_affinity is not None
                )

            need_ports = any(p.host_ports() for p in pods)
            need_spread = any(p.topology_spread_constraints for p in pods)
            # PodTopologySpread defaultingType=System: service-selected pods
            # without explicit constraints get soft cluster defaults
            services = (
                self.cluster.list_services()
                if solver.config.spread_defaulting == "System"
                else []
            )
            if services and not need_spread:
                from .ops.oracle.spread import default_selector

                need_spread = any(
                    not p.topology_spread_constraints
                    and default_selector(p, services) is not None
                    for p in pods
                )
            need_interpod = any(has_pod_affinity(p) for p in pods) or any(
                info.pods_with_affinity
                for info in self.cache.nodes.values()
                if info.node is not None
            )
            # Pad the pod axis to the configured batch size so every cycle —
            # including the final partial batch — reuses ONE compiled shape
            # (§8.8 recompile storms). All-padding chunks are near-free in the
            # grouped solver's fast path, so the fixed bucket only pays off when
            # that path can engage (mirror of the solver's dispatch condition);
            # otherwise the per-pod scan would walk every padding step, so keep
            # the tight pow2 bucket.
            from .solver.exact import grouped_eligible

            # nominated pods force the per-pod scan (grouped_eligible), so
            # detect them before committing to the fixed pod-axis bucket
            nom_pairs = []
            for q in self.nominated_pods.values():
                try:
                    nom_pairs.append(
                        (q, self.snapshot.slot_of(q.nominated_node_name))
                    )
                except KeyError:
                    continue  # nominated node no longer in the snapshot

            # mirror the tensor-level groupable facts from the pods (solve
            # recomputes them from the tensors; disagreement degrades to
            # padded-slow, never wrong): hard-only spread with no soft
            # constraints / no service defaults; anti-affinity-only interpod
            spread_groupable = need_spread and not services and all(
                all(
                    c.when_unsatisfiable == "DoNotSchedule"
                    for c in p.topology_spread_constraints
                )
                for p in pods
            )
            interpod_groupable = need_interpod and all(
                p.affinity is None
                or (
                    p.affinity.pod_affinity is None
                    and (
                        p.affinity.pod_anti_affinity is None
                        or not p.affinity.pod_anti_affinity.preferred
                    )
                )
                for p in pods
            )
            grouped_ok = grouped_eligible(
                solver.config, self.config.batch_size, batch.padded,
                need_spread, need_interpod, bool(nom_pairs),
                spread_groupable=spread_groupable,
                interpod_groupable=interpod_groupable,
            )
            pod_pad = (
                self.config.batch_size
                if grouped_ok and len(pods) <= self.config.batch_size
                else None
            )
            # per-plugin host tensorization timings feed the reference's
            # plugin_execution_duration_seconds series: inside the fused device
            # program per-plugin attribution doesn't exist, but the host-side
            # per-plugin-family tensorizers are real measured work
            def _timed(plugin: str, fn, *a, **kw):
                tp = self.clock.perf()
                out = fn(*a, **kw)
                metrics.plugin_execution_duration_seconds.labels(
                    plugin, "PreFilter", "Success"
                ).observe(self.clock.perf() - tp)
                return out

            pbatch = _timed(
                "NodeResourcesFit", build_pod_batch, pods, batch.vocab, pad=pod_pad
            )

            # Node objects in snapshot-slot order, for the plugin tensorizers
            # (share the solver's node index space).
            slot_nodes = []
            for name in self.snapshot.names:
                info = self.cache.nodes.get(name) if name else None
                slot_nodes.append(info.node if info is not None else None)

            volume_ctx = None
            if any(p.pvc_names for p in pods):
                from .ops.oracle.volumes import VolumeContext

                volume_ctx = VolumeContext.build(
                    self.cluster.list_pvs(),
                    self.cluster.list_pvcs(),
                    {
                        info.node.name: list(info.pods.values())
                        for info in self.cache.nodes.values()
                        if info.node is not None and info.pods
                    },
                )
            class_key_extra = None
            if services:
                from .ops.oracle.spread import default_selector_key

                def class_key_extra(p):
                    if p.topology_spread_constraints:
                        return None
                    return default_selector_key(p, services)

            dra_active = self._dra and any(
                p.resource_claim_names or p.claim_templates_unresolved
                for p in pods
            )
            if dra_active:
                # pods with different claim sets must not share a class
                # rep: the DRA mask is per-claim-set
                base_dra = class_key_extra

                def class_key_extra(p, _base=base_dra):
                    parts = (
                        p.namespace,
                        tuple(sorted(p.resource_claim_names)),
                        p.claim_templates_unresolved,
                    )
                    if _base is not None:
                        return (parts, _base(p))
                    return parts

            if self.config.out_of_tree_plugins or self.extender_clients:
                # custom plugins and extenders read pod fields the in-tree
                # class key doesn't cover (labels/annotations on spread-free
                # pods): fold them into the class identity so two pods such a
                # consumer would treat differently never share one
                # representative's verdicts. (Plugins must key off spec
                # fields in the class identity — framework/interface.py
                # documents the contract; extenders see the rep's full JSON.)
                base_extra = class_key_extra

                def class_key_extra(p, _base=base_extra):
                    parts = (
                        tuple(sorted(p.labels.items())),
                        tuple(sorted(p.annotations.items())),
                    )
                    if _base is not None:
                        return (parts, _base(p))
                    return parts

            if (
                self._gang is not None
                and self._gang.config.class_throughput
                and self._gang.config.throughput_weight > 0
            ):
                # heterogeneity objective (gang/throughput.py): pods of
                # different workload classes score differently per
                # accelerator class, so they must not share a class rep
                from .gang import WORKLOAD_CLASS_LABEL

                base_gang = class_key_extra

                def class_key_extra(p, _base=base_gang):
                    parts = (p.labels.get(WORKLOAD_CLASS_LABEL),)
                    if _base is not None:
                        return (parts, _base(p))
                    return parts

            static = _timed(
                "NodeAffinity",  # the static-mask family's dominant member
                build_static_tensors,
                pods, pbatch, slot_nodes, batch.padded, volume_ctx,
                disabled=frozenset(solver.config.disabled_filters),
                added_affinity=solver.config.added_affinity,
                class_key_extra=class_key_extra,
            )
            placed_by_slot: dict[int, list[Pod]] = {}
            if need_ports or need_spread or need_interpod:
                for slot, name in enumerate(self.snapshot.names):
                    info = self.cache.nodes.get(name) if name else None
                    if info is not None and info.node is not None and info.pods:
                        placed_by_slot[slot] = list(info.pods.values())
            if need_ports:
                ports = _timed(
                    "NodePorts", build_port_tensors,
                    pods, pbatch, slot_nodes, placed_by_slot, batch.padded,
                    nominated=nom_pairs,
                    # occupancy staging reuse: valid while the cache is
                    # byte-unchanged since the staged scan (any watch
                    # event or apply bumps the generation) and the slot
                    # layout is identical — the streaming burst window
                    staging=self._port_staging,
                    staging_key=(self.cache.generation, batch.padded),
                )
            else:
                ports = trivial_port_tensors(pbatch, batch.padded)
            # spread/interpod count nominated pods host-side with no
            # device-side self-exclusion (unlike ports' nominated_slot), so
            # drop batch pods' own nominations — a pod must not see itself
            # as an already-standing peer
            if need_spread or need_interpod:
                _batch_keys = {p.key for p in pods}
                nom_peers = [
                    (q, s) for q, s in nom_pairs if q.key not in _batch_keys
                ]
            spread = None
            if need_spread:
                spread = _timed(
                    "PodTopologySpread", build_spread_tensors,
                    pods, static.reps, pbatch, slot_nodes,
                    placed_by_slot, batch.padded, static.c_pad,
                    services=services,
                    defaulting=solver.config.spread_defaulting,
                    nominated=nom_peers,
                )
            interpod = None
            if need_interpod:
                interpod = _timed(
                    "InterPodAffinity", build_interpod_tensors,
                    pods, static.reps, pbatch, slot_nodes,
                    placed_by_slot, batch.padded, static.c_pad,
                    hard_pod_affinity_weight=solver.config.hard_pod_affinity_weight,
                    nominated=nom_peers,
                )

            # nominated-pod load (RunFilterPluginsWithNominatedPods analog):
            # unbound pods carrying a nomination count as placed on their
            # nominated node for higher/equal-priority peers; pods in THIS
            # batch that are themselves nominated get a per-pod slot for the
            # evaluateNominatedNode-first pick and self-exclusion
            from .tensorize.schema import build_nominated_tensors

            nominated = build_nominated_tensors(
                nom_pairs, batch.vocab, batch.padded,
                ports=ports if need_ports else None,
            )
            nominated_slot = None
            if not nominated.empty:
                # batch pods carrying a nomination are in nom_pairs (same
                # objects, same slot resolution) — reuse, don't re-resolve
                slot_by_key = {p.key: slot for p, slot in nom_pairs}
                nominated_slot = np.full(len(pods), -1, dtype=np.int32)
                for i, p in enumerate(pods):
                    nominated_slot[i] = slot_by_key.get(p.key, -1)

            return _PreparedGroup(
                profile=profile, infos=infos, pods=pods,
                cycle_offsets=cycle_offsets, base_cycle=base_cycle,
                t0=t0, gs=gs, batch=batch, pbatch=pbatch, static=static,
                ports=ports, spread=spread, interpod=interpod,
                nominated=nominated, nominated_slot=nominated_slot,
                slot_nodes=slot_nodes, names=list(self.snapshot.names),
                volume_ctx=volume_ctx, services=services,
                dra_active=dra_active, fence=self._conflict_seq,
                occ_fence=self._occupancy_seq,
                occ_sensitive=bool(
                    need_ports
                    or need_spread
                    or need_interpod
                    or dra_active
                    or volume_ctx is not None
                    or nom_pairs
                ),
                step=self._trace_step,
            )

    def _fold_group(self, prep: _PreparedGroup) -> None:
        """Out-of-tree plugin + extender + DRA folding, OUTSIDE the
        cluster lock (arbitrary user code / HTTP round trips must not
        block ingest); it only touches the host-side static tables and
        immutable Node snapshots gathered at tensorize time."""
        static = prep.static
        slot_nodes = prep.slot_nodes
        pods = prep.pods
        dra_active = prep.dra_active
        dra_prefold = prep.dra_prefold
        unsched_reason = prep.unsched_reason
        if self.config.out_of_tree_plugins:
            # out-of-tree Scheduling Framework plugins: class-vectorized
            # folding into the static mask / extra-score tables. A
            # filter-only plugin set keeps extra_score=None so the fused
            # kernel's extra-add (and its compile variant) never engages.
            # Memoized on (plugin set, class-rep signature, node objects,
            # input mask): serve-mode batches of identical pod classes
            # against an unchanged cluster skip the O(classes x nodes)
            # Python re-run. Sound because solver-path plugins are pure
            # per (class identity, node) by the documented contract.
            from .framework.runtime import fold_out_of_tree

            sig = self._fold_signature(static, slot_nodes)
            cached = self._fold_cache.get(sig)
            # the cache holds STRONG refs to the node objects it hashed,
            # so a live entry's id()s cannot be recycled; the identity
            # re-check makes a hash collision with a dead generation
            # impossible to act on (review-caught id-reuse hazard)
            if cached is not None and len(cached[2]) == len(
                slot_nodes
            ) and all(a is b for a, b in zip(cached[2], slot_nodes)):
                self._fold_cache[sig] = self._fold_cache.pop(sig)  # LRU
                static.mask[:] = cached[0]
                if cached[1] is not None:
                    static.extra_score = cached[1].copy()
                metrics.fold_cache_total.labels("hit").inc()
            else:
                metrics.fold_cache_total.labels("miss").inc()
                extra = np.zeros(static.mask.shape, dtype=np.int32)
                fold_out_of_tree(
                    self.config.out_of_tree_plugins, static.reps,
                    slot_nodes, static.mask, extra,
                )
                if extra.any():
                    static.extra_score = extra
                if len(self._fold_cache) >= 8:
                    self._fold_cache.pop(next(iter(self._fold_cache)))
                self._fold_cache[sig] = (
                    static.mask.copy(),
                    extra.copy() if extra.any() else None,
                    list(slot_nodes),
                )
        if self.extender_clients:
            # findNodesThatPassExtenders + prioritizeNodes' extender pass,
            # folded per scheduling class like out-of-tree plugins (one
            # wire round trip per class+extender+verb per batch)
            from .server.extender_client import fold_extenders

            extra = (
                static.extra_score
                if static.extra_score is not None
                else np.zeros(static.mask.shape, dtype=np.int32)
            )
            if self.obs.enabled:
                # cross-process trace propagation: the webhook round
                # trips carry this batch's trace context so an
                # extender server sharing the obs layer attributes its
                # micro-batched evaluation to OUR trace (obs off =
                # unchanged wire bytes)
                cur = self.obs.current()
                tctx = {
                    "trace": prep.step,
                    "parent": cur.span_id if cur is not None else None,
                    "replica": (
                        self.fleet.replica if self.fleet is not None else ""
                    ),
                    "incarnation": self.config.incarnation,
                }
                for cl in self.extender_clients:
                    cl.trace_context = tctx
            try:
                fold_extenders(
                    self.extender_clients, static.reps, slot_nodes,
                    static.mask, extra,
                )
            finally:
                if self.obs.enabled:
                    for cl in self.extender_clients:
                        cl.trace_context = None
            if extra.any():
                static.extra_score = extra
        if self._gang is not None:
            # heterogeneity-aware scoring (gang/throughput.py): Gavel's
            # effective-throughput objective accumulates into the same
            # generic extra_score donor the folds above use, so every
            # solver path (fused + grouped) applies it with zero new
            # kernel surface. AFTER the fold-cache block (a cache hit
            # REPLACES extra_score) and the extender fold; BEFORE the
            # DRA mask fold, which only touches the mask.
            from .gang import fold_throughput

            fold_throughput(static, slot_nodes, self._gang.config)
        if dra_active:
            # dynamicresources Filter: fold per-class claim feasibility
            # into the static mask (allocated claims pin to their node).
            # Runs AFTER the out-of-tree/extender folds so the preemption
            # widen mask below already carries their rejections (widening
            # must never resurrect a node an extender vetoed), and keeps
            # their mask-keyed memo stable. The allocator's cached
            # context is reused — dra_generation-keyed build plus the
            # in-flight assumption overlay, so devices taken by pods
            # still binding are already masked out.
            from .ops.oracle.dra import ClaimError

            tdra = self.clock.perf()
            dra_ctx = self.claim_allocator.context()
            unresolvable: dict[int, str] = {}
            for ci, rep in enumerate(static.reps):
                if not (
                    rep.resource_claim_names
                    or rep.claim_templates_unresolved
                ):
                    continue
                try:
                    m = dra_ctx.feasible_mask(rep, slot_nodes)
                except ClaimError as e:
                    # UnschedulableAndUnresolvable: mask the class and
                    # surface the REASON on the pods' failure events
                    m = False
                    unresolvable[ci] = str(e)
                else:
                    # device exhaustion is Unschedulable, NOT
                    # Unresolvable: preemption may free devices, so
                    # candidate selection widens back to the pre-DRA
                    # mask (with a victims-release recheck —
                    # _dra_preempt_ok)
                    dra_prefold[ci] = static.mask[ci].copy()
                static.mask[ci] &= m
            if unresolvable:
                class_of = np.asarray(static.class_of)
                for i, p in enumerate(pods):
                    why = unresolvable.get(int(class_of[i]))
                    if why is not None:
                        unsched_reason[p.key] = why
            metrics.plugin_execution_duration_seconds.labels(
                "DynamicResources", "PreFilter", "Success"
            ).observe(self.clock.perf() - tdra)
    def _dispatch_group(
        self,
        prep: _PreparedGroup,
        defer: bool,
        allow_heal: bool = True,
        split: int = 1,
        tier: str | None = None,
        stream: bool = False,
        chain: bool = False,
        chain_key: tuple | None = None,
    ) -> "_InFlightSolve | list[_InFlightSolve]":
        """Upload + launch the device solve. ``defer=False`` blocks on
        the assignment read (the synchronous path); ``defer=True``
        returns immediately with an async device→host copy in flight so
        the read overlaps later host work (run_pipelined).
        ``allow_heal=False`` defers dirty-column healing while an
        earlier solve is still unapplied (see _DeviceSession.sync).
        ``split > 1`` (deferred only) dispatches the batch as chained
        sub-solves (ExactSolver.solve's RTT-hiding batch split) and
        returns one in-flight solve per sub-batch, all sharing this
        prep and its fences. ``tier`` (the resilient synchronous path)
        pins the fallback-ladder rung: TIER_MESH/None keep the
        configured mesh, TIER_SINGLE drops to one device, TIER_CPU
        additionally forces the CPU backend; None means the top tier.
        ``stream``/``chain``/``chain_key`` (run_streaming): keep the
        solve's full carried state device-resident as the session's
        stream carry, and — with ``chain`` — consume the PREVIOUS
        batch's resident carry instead of uploading host occupancy
        rows (ExactSolver.solve's cross-batch chain)."""
        solver = self.solvers[prep.profile]
        tier_name = tier or self.resilience.ladder[0]
        with self.cluster.lock:
            heal_stale = prep.profile in self._session_stale and allow_heal
            if heal_stale:
                self._session_stale.discard(prep.profile)
        if heal_stale:
            # a discarded solve polluted the device carry; with no other
            # solve in flight (allow_heal implies the pipeline drained),
            # re-upload from host truth before dispatching. The flag is
            # cleared under the lock, the device reset runs outside it
            # (only the drain thread resets sessions)
            solver.reset_session()
        if self._tier_last.get(prep.profile) != tier_name:
            # a ladder-tier change moves the solve (and its resident
            # session state) to a different device set: re-upload from
            # host truth. Only the drain/sync thread changes tiers, so
            # no other solve is in flight here.
            solver.reset_session()
            self._tier_last[prep.profile] = tier_name
        hook = self._solve_fault
        if hook is not None:
            # sim seam: after the heal bookkeeping (a raise here must
            # not strand a consumed stale flag), before the solve
            hook(prep.pods, tier_name)
        mesh = self.mesh if tier_name == TIER_MESH else None
        t1 = self.clock.perf()
        # backlog drains thread the chunk id into the dispatch span so
        # `obs explain` can attribute a pod to its drain chunk
        span_extra = (
            {
                "drain_chunk": prep.step - self._drain_chunk_base,
                # the drain's root trace id: ties every chunk's spans
                # into ONE drain trace (set by drain_backlog)
                "drain_trace": self._drain_chunk_base,
            }
            if self._backlog_drain_active
            else {}
        )
        # session mode: node tables + carried state stay device-resident;
        # dirty snapshot columns heal by version; only assignments download
        #
        # compile attribution (obs/compile.py): any XLA compile firing
        # inside this bracket counts against the dispatch's shape/
        # static fingerprint — a steady-state batch re-compiling a
        # known shape is the silent hot-path killer the watcher's
        # recompilation gauge (and the known-shape regression test)
        # exists to catch. The span gets the delta as attributes when
        # a compile actually happened.
        compile_scope = self._compile_watcher.scope(
            f"{prep.profile}:p{prep.pbatch.padded}xn{prep.batch.padded}"
            f":split{split}:{tier_name}"
        )
        if self.telemetry is not None and self.telemetry.bundles is not None:
            # telemetry capture arm: the solver's capture_hook payload
            # that fires inside solve() below belongs to this batch step
            self.telemetry.bundles.arm(prep.step, prep.profile)
        with self.obs.span(
            "dispatch", trace_id=prep.step, profile=prep.profile,
            defer=defer, healed=heal_stale, split=split,
            mesh_devices=self._mesh_devices, **span_extra,
        ) as dsp, _tier_device_context(tier_name), compile_scope:
            handle = solver.solve(
                prep.batch, prep.pbatch, prep.static, prep.ports,
                prep.spread, prep.interpod,
                col_versions=self.snapshot.col_versions,
                nominated=prep.nominated if not prep.nominated.empty else None,
                nominated_slot=prep.nominated_slot,
                defer_read=defer,
                allow_heal=allow_heal,
                split=split,
                mesh=mesh,
                chain_occupancy=chain,
                stream_carry_out=stream,
                chain_key=chain_key,
            )
            n_compiles, compile_s = compile_scope.delta()
            if n_compiles:
                dsp.set(
                    xla_compiles=n_compiles,
                    xla_compile_s=round(compile_s, 6),
                )
        dispatch_dt = self.clock.perf() - t1
        if self.telemetry is not None:
            self.telemetry.add_stage("dispatch", dispatch_dt)
        if not prep.timing_observed:
            prep.timing_observed = True
            prep.tensorize_seconds = max(t1 - prep.gs, 0.0)
            if self.telemetry is not None:
                self.telemetry.add_stage(
                    "tensorize", prep.tensorize_seconds
                )
            metrics.tensorize_seconds.observe(prep.tensorize_seconds)
            # extension-point durations with the reference's metric
            # names: host tensorization maps to PreFilter (documented,
            # SURVEY §6.5)
            metrics.framework_extension_point_duration_seconds.labels(
                "PreFilter", "Success", prep.profile
            ).observe(prep.tensorize_seconds)
        if isinstance(handle, list):
            # chained sub-solves (split > 1, or any streaming dispatch —
            # the stream path returns a list even unsplit): one flight
            # per sub-batch, sharing the prep. The chain's dispatch wall
            # spreads EVENLY across the sub-flights (totals stay honest,
            # and the adaptive-split estimator's per-pod rate isn't
            # inflated by charging the whole chain's dispatch to one
            # sub-batch); the shared tensorize cost reports on the first
            # flight only.
            share = dispatch_dt / len(handle)
            flights = [
                _InFlightSolve(
                    prep=prep,
                    handle=h,
                    dispatch_seconds=share,
                    lo=h.lo,
                    hi=h.lo + h.count,
                    tensorize_share=None if i == 0 else 0.0,
                )
                for i, h in enumerate(handle)
            ]
            if len(flights) > 1:
                # a clamped split (indivisible padding, nominated batch)
                # is NOT a chain: counting it would let a regression
                # that always clamps keep the chain metric (and the
                # tests reading it) green
                metrics.pipeline_subbatches_total.inc(len(flights))
            hook = self._post_dispatch_hook
            if hook is not None:
                # per sub-flight, honoring the seam's contract ("after
                # every dispatch"): the sim gets one fault-injection
                # point per dispatch→apply window, so mid-chain fence
                # interleavings are reachable from the smokes too
                for f in flights:
                    hook(f)
            return flights
        flight = _InFlightSolve(
            prep=prep, handle=handle, dispatch_seconds=dispatch_dt,
        )
        hook = self._post_dispatch_hook
        if hook is not None:
            hook(flight)
        return flight

    def _apply_group(
        self,
        flight: _InFlightSolve,
        res: BatchResult,
        pending: list,
        fence: int | None = None,
    ) -> bool:
        """Phase 2b (locked): read the assignments and apply them —
        assume / Reserve / Permit / PostFilter — atomically with the
        watch-event consumers. With ``fence`` set (pipelined path), the
        fence is RE-CHECKED inside the lock — a conflicting event can
        land during the unlocked device read — and a stale solve applies
        nothing and returns False (the caller discards). The synchronous
        path passes no fence: its solve-window staleness is the same one
        the reference's binding goroutines accept."""
        prep = flight.prep
        profile = prep.profile
        solver = self.solvers[profile]
        # a chained sub-flight covers prep pods [lo, hi); idx below is
        # slice-local — pod-indexed prep tensors use pod_base + idx
        pod_base = flight.lo
        infos, pods = flight.infos(), flight.pods()
        static, slot_nodes = prep.static, prep.slot_nodes
        volume_ctx, services = prep.volume_ctx, prep.services
        dra_active, dra_prefold = prep.dra_active, prep.dra_prefold
        unsched_reason = prep.unsched_reason
        base_cycle, cycle_offsets = prep.base_cycle, flight.cycle_offsets()
        t0, gs = prep.t0, prep.gs
        pending_before = len(pending)
        unsched_before = len(res.unschedulable)
        failures_before = len(res.bind_failures)
        tr = self.clock.perf()
        try:
            assignments = flight.assignments()
        except Exception as e:
            # the deferred device→host read itself died (session /
            # transfer loss after dispatch): surface it as a solver
            # fault so the resilience layer owns the retry instead of
            # the loop crashing (kubernetes_tpu/resilience)
            raise SolverReadError(
                f"deferred assignment read failed: {e!r}"
            ) from e
        flight.read_seconds = self.clock.perf() - tr
        if self.telemetry is not None:
            self.telemetry.add_stage("deferred_read", flight.read_seconds)
        solve_dt = flight.dispatch_seconds + flight.read_seconds
        res.solve_seconds += solve_dt
        # the fused device program IS RunFilterPlugins+RunScorePlugins, so
        # its dispatch+read wall time reports under Filter (SURVEY §6.5)
        metrics.framework_extension_point_duration_seconds.labels(
            "Filter", "Success", profile
        ).observe(solve_dt)

        with self.cluster.lock, self.obs.span(
            "apply", trace_id=prep.step, profile=profile, pods=len(infos),
            read_seconds=flight.read_seconds,
        ) as asp:
            if fence is not None and (
                fence != self._conflict_seq
                or (
                    prep.occ_sensitive
                    and prep.occ_fence != self._occupancy_seq
                )
            ):
                asp.set(fence_stale=True)
                return False  # went stale during the device read
            if self.resilience.config.validate:
                # pre-apply output validation (resilience.py): a
                # silently-corrupt solve is a solve FAILURE feeding the
                # breaker, never applied. Runs after the fence check so
                # prep-time capacity can only have been FREED since the
                # solve (capacity-consuming events discard first) — a
                # flagged overcommit is always corruption, not churn.
                tv = self.clock.perf()
                why = validate_assignments(
                    prep, flight.lo, assignments,
                    disabled=frozenset(solver.config.disabled_filters),
                )
                if self.telemetry is not None:
                    self.telemetry.add_stage(
                        "validate", self.clock.perf() - tv
                    )
                if why is not None:
                    raise SolveCorruptError(why)
            t_apply = self.clock.perf()
            if self.telemetry is not None and self.telemetry.bundles is not None:
                # the flight applied (fence passed, output validated):
                # its assignment slice is what a bundle replay of this
                # batch must reproduce bit-identically
                self.telemetry.bundles.note_assignments(
                    prep.step, flight.lo, assignments
                )
            # phase 2b: apply assignments — assume / Reserve / Permit /
            # PostFilter — atomically with the watch-event consumers
            preempt_placed: dict[int, list[Pod]] | None = None
            preempt_pdbs: list = []
            cluster_has_affinity = False
            postfilter_reasons: dict | None = None
            preempt_dt = 0.0
            preempt_ran = False  # a zero-duration run (FakeClock) still
            # counts as an observation — gating on the float hid the
            # PostFilter series from virtual-time runs
            bind_dt = 0.0
            # FitError diagnosis (schedule_one.go#FitError [U]): per-node
            # reasons don't exist inside the fused device pipeline, so the
            # failure path replays the scalar oracle's filters to build the
            # reference-shaped "0/N nodes are available: k Insufficient
            # cpu, ..." message. Lazy (failures only) and memoized on
            # (class, requests) — pods sharing constraint class AND
            # request vector share the diagnosis.
            fit_oracle = None
            fiterr_memo: dict[tuple, str] = {}
            # ktpu: ignore[TPU001]: static.class_of is a host-resident numpy table from tensorize — no device transfer happens here
            class_of_host = np.asarray(static.class_of)
            fe_nodes = sum(1 for n in slot_nodes if n is not None)
            fe_generic = (
                f"0/{fe_nodes} nodes are available: the batched "
                "filter pipeline rejected every candidate"
            )

            def fit_error_for(pod: Pod, idx: int) -> str:
                nonlocal fit_oracle
                # claims are already folded into the class identity when
                # DRA is active (class_key_extra); with DRA off they can't
                # influence the diagnosis, so keying them then would only
                # fragment the 16-entry replay budget
                key = (
                    int(class_of_host[idx]),
                    tuple(sorted(pod.resource_request().items())),
                    pod.host_ports(),  # ports are per-pod, not class-level
                    tuple(sorted(pod.resource_claim_names))
                    if dra_active
                    else (),
                )
                msg = fiterr_memo.get(key)
                if msg is not None:
                    return msg
                # the oracle replay is O(nodes x plugins) scalar Python on
                # a 1-vCPU host: bound the diagnosis work per batch so a
                # pathological batch of many distinct failing shapes can't
                # stall the scheduling loop (later shapes get the generic
                # message; their retry in a later batch gets a fresh budget)
                if len(fiterr_memo) >= 16:
                    return fe_generic
                if fit_oracle is None:
                    from .ops.oracle.profile import (
                        FullOracle,
                        make_oracle_nodes,
                    )

                    live = [n for n in slot_nodes if n is not None]
                    by_name = {
                        info2.node.name: list(info2.pods.values())
                        for info2 in self.cache.nodes.values()
                        if info2.node is not None and info2.pods
                    }
                    fit_oracle = FullOracle(
                        make_oracle_nodes(live, by_name),
                        volume_ctx=volume_ctx,
                        services=services,
                        spread_defaulting=solver.config.spread_defaulting,
                        disabled=frozenset(solver.config.disabled_filters),
                    )
                extra = None
                if dra_active and pod.resource_claim_names:
                    # the scalar replay has no DRA filter: contribute the
                    # claim-feasibility verdicts for nodes it accepts
                    try:
                        dm = self.claim_allocator.context().feasible_mask(
                            pod, slot_nodes
                        )
                        ok_by_name = {
                            n.name: bool(dm[i])
                            for i, n in enumerate(slot_nodes)
                            if n is not None
                        }

                        def extra(on):
                            if ok_by_name.get(on.node.name, True):
                                return None
                            return (
                                "node(s) cannot allocate the pod's "
                                "resourceclaim devices"
                            )
                    except Exception:
                        extra = None
                try:
                    msg = fit_oracle.fit_error(pod, extra=extra)
                except Exception:
                    msg = fe_generic
                if msg.endswith("nodes are available"):
                    # every scalar filter accepted some node: the rejection
                    # came from a folded filter the replay can't attribute
                    # (out-of-tree plugin / extender verdict) — stay honest
                    # instead of implying the cluster is full
                    msg = fe_generic
                fiterr_memo[key] = msg
                return msg
            gang_staged = 0
            for idx, (info, a) in enumerate(zip(infos, assignments)):
                pod = info.pod
                cycle = base_cycle + cycle_offsets[idx] + 1
                # gang members STAGE instead of entering pending, and
                # any failure marks their whole round failed — the
                # all-or-nothing resolution happens in _commit_all
                rd = self._gang_round_of(pod)
                if a < 0:
                    # failure path: PostFilter — defaultpreemption first, then
                    # out-of-tree PostFilter plugins (first success nominates)
                    nominated_node = None
                    if self.config.enable_preemption:
                        preempt_ran = True
                        if preempt_placed is None:
                            # shared across this batch's failures: occupancy
                            # snapshot, PDB list, and the cluster-wide
                            # pods-with-affinity flag (avoid per-pod rescans)
                            preempt_placed = self._placed_by_slot()
                            preempt_pdbs = self.cluster.list_pdbs()
                            cluster_has_affinity = any(
                                i2.pods_with_affinity
                                for i2 in self.cache.nodes.values()
                                if i2.node is not None
                            )
                        tpf = self.clock.perf()
                        nominated_node = self._try_preempt(
                            pod, static, pod_base + idx, res,
                            preempt_placed, slot_nodes,
                            preempt_pdbs, cluster_has_affinity, solver,
                            dra_prefold=dra_prefold,
                        )
                        preempt_dt += self.clock.perf() - tpf
                    if nominated_node is None and self.registry.post_filter:
                        preempt_ran = True
                        if postfilter_reasons is None:
                            # NodeToStatusMap analog, shared across this
                            # batch's failures: per-node reasons don't exist
                            # inside the fused pipeline, so every candidate
                            # carries the batch-level rejection
                            postfilter_reasons = {
                                n.name: "node did not satisfy the batched "
                                "filter pipeline"
                                for n in slot_nodes
                                if n is not None
                            }
                        tpf = self.clock.perf()
                        # fresh copy per pod: upstream's NodeToStatusMap is
                        # per-pod scratch a plugin may legitimately mutate
                        self._run_post_filter(pod, dict(postfilter_reasons))
                        preempt_dt += self.clock.perf() - tpf
                    res.unschedulable.append(pod.key)
                    self._requeue(info, cycle)
                    self._gang_note_fail(rd, pod, "unschedulable")
                    why = unsched_reason.get(pod.key) or fit_error_for(
                        pod, pod_base + idx
                    )
                    self._event(
                        pod, "FailedScheduling", why, type_="Warning",
                    )
                    if self.journal is not None:
                        self.journal.unschedulable(
                            prep.step, cycle, pod, prep, pod_base + idx,
                            reason=why, nominated=nominated_node or "",
                            attempts=info.attempts,
                        )
                    continue
                node_name = prep.names[int(a)]
                if self.fleet is not None:
                    # cross-shard admission (fleet/reconciler.py):
                    # ownership fence + occupancy recheck against
                    # peers' exchanged rows. A rejection is the
                    # fleet's Conflict-on-stale: requeue and retry,
                    # never block the fleet. The device session's
                    # carry counted the placement, so it heals before
                    # the next dispatch.
                    fleet_why = self.fleet.admit(pod, node_name, self.cache)
                    if fleet_why is not None:
                        self._session_stale.add(profile)
                        # trace propagation across the handoff: mint
                        # (or reuse) the pod's journey trace BEFORE the
                        # release so it rides the handoff row — the
                        # adopting replica's journal continues the SAME
                        # trace and `obs explain --fleet` renders one
                        # enqueue→handoff→re-admit→bind chain
                        pod_trace = ""
                        if self.journal is not None:
                            pod_trace = self.journal.pod_traces.get(
                                pod.key
                            ) or (
                                f"{self.journal.origin}:{prep.step}"
                                f":{pod.key}"
                            )
                            self.journal.pod_traces[pod.key] = pod_trace
                        handed_to = (
                            self.fleet.maybe_hand_off(
                                pod, trace=pod_trace
                            )
                            if rd is None
                            # gang members never hand off alone: the
                            # group must land together, so a rejected
                            # member retries locally with its siblings
                            else None
                        )
                        if handed_to is not None:
                            # released to a peer whose shard may host
                            # it: drop every local claim on the pod
                            # (its watch events now route to the peer)
                            self._in_flight.pop(pod.key, None)
                            self.queue.delete(pod.key)
                            if self.journal is not None:
                                self.journal.record(
                                    prep.step, cycle, pod, "discarded",
                                    node=node_name, profile=profile,
                                    reason=(
                                        f"handed off to {handed_to}: "
                                        + fleet_why
                                    ),
                                    attempts=info.attempts,
                                )
                                # the peer owns the journey now; keep
                                # no local trace entry behind
                                self.journal.pod_traces.pop(
                                    pod.key, None
                                )
                            continue
                        res.unschedulable.append(pod.key)
                        self._requeue(info, cycle)
                        self._gang_note_fail(rd, pod, fleet_why)
                        self._event(
                            pod, "FailedScheduling", fleet_why,
                            type_="Warning",
                        )
                        if self.journal is not None:
                            self.journal.record(
                                prep.step, cycle, pod, "unschedulable",
                                node=node_name, reason=fleet_why,
                                profile=profile, attempts=info.attempts,
                            )
                        continue
                try:
                    self.cache.assume_pod(pod, node_name)
                except Exception as e:  # cache inconsistency: requeue
                    # the device-resident solve DID place the pod; mark the
                    # column dirty so the session re-heals it from cache truth
                    self.snapshot.touch(int(a))
                    if self.fleet is not None:
                        # admit() may have CAS-staged the pending row at
                        # the hub already; a placement that never gets
                        # assumed must not keep distorting peers'
                        # admission until the next resync
                        self.fleet.withdraw(pod.key)
                    res.bind_failures.append((pod.key, str(e)))
                    self._requeue(info, cycle)
                    self._gang_note_fail(rd, pod, str(e))
                    if self.journal is not None:
                        self.journal.record(
                            prep.step, cycle, pod, "bind_failure",
                            node=node_name, reason=str(e), profile=profile,
                            attempts=info.attempts,
                        )
                    continue
                if self.fleet is not None:
                    # publish the assumed placement to the occupancy
                    # exchange so peers' admissions count it; every
                    # rollback path routes through _unreserve_all,
                    # which withdraws the row
                    self.fleet.stage(pod, node_name, self.cache)

                # Reserve point: in-tree volumebinding Reserve
                # (AssumePodVolumes) then out-of-tree ReservePlugins in
                # registration order; any failure unreserves everything
                # (reverse order), forgets the assume, and requeues
                state = CycleState()
                try:
                    tb = self.clock.perf()
                    if pod.pvc_names:
                        ninfo = self.cache.nodes.get(node_name)
                        if ninfo is None or ninfo.node is None:
                            raise VolumeBindingError(
                                f"node {node_name} vanished before volume binding"
                            )
                        self.volume_binder.assume_pod_volumes(pod, ninfo.node)
                    if self._dra and (
                        pod.resource_claim_names
                        or pod.claim_templates_unresolved
                    ):
                        # dynamicresources Reserve: assume concrete devices
                        # on the chosen node (the mask said they exist; a
                        # same-batch racer may have taken them — fail =>
                        # unreserve + requeue, like the reference's
                        # in-flight claim conflicts)
                        self.claim_allocator.assume_pod_claims(
                            pod, node_name
                        )
                    for p in self.registry.reserve:
                        st = p.reserve(state, pod, node_name)
                        if not st.is_success:
                            raise _Rejected(
                                f"Reserve plugin {p.name()} rejected: "
                                + "; ".join(st.reasons)
                            )
                    bind_dt += self.clock.perf() - tb
                except (
                    VolumeBindingError, ClaimAllocationError, _Rejected,
                ) as e:
                    self._unreserve_all(state, pod, node_name)
                    res.bind_failures.append((pod.key, str(e)))
                    self._requeue(info, cycle)
                    self._gang_note_fail(rd, pod, str(e))
                    self._event(
                        pod, "FailedScheduling", str(e), type_="Warning",
                    )
                    if self.journal is not None:
                        self.journal.record(
                            prep.step, cycle, pod, "bind_failure",
                            node=node_name, reason=str(e), profile=profile,
                            attempts=info.attempts,
                        )
                    continue

                # Permit point: approve / reject / wait
                # (framework.go#RunPermitPlugins); WAIT parks the pod in the
                # WaitingPods map — it stays assumed (+reserved) and the
                # binding completes or rolls back in a later cycle
                verdict = self._run_permit(state, pod, node_name)
                if isinstance(verdict, dict) and rd is not None:
                    # Permit WAIT is unsupported for pod-group members
                    # (documented limitation): a parked member would
                    # hold every sibling's staged placement hostage
                    # across cycles — convert to a rejection so the
                    # round resolves this batch and the gang retries
                    permit_why = (
                        "Permit WAIT is unsupported for pod-group "
                        "members (plugins: "
                        + ",".join(sorted(verdict)) + ")"
                    )
                    self._unreserve_all(state, pod, node_name)
                    res.unschedulable.append(pod.key)
                    self._requeue(info, cycle)
                    self._gang_note_fail(rd, pod, permit_why)
                    self._event(
                        pod, "FailedScheduling", permit_why,
                        type_="Warning", action="Permit",
                    )
                    if self.journal is not None:
                        self.journal.record(
                            prep.step, cycle, pod, "permit_rejected",
                            node=node_name, reason=permit_why,
                            profile=profile, attempts=info.attempts,
                        )
                    continue
                if isinstance(verdict, dict):
                    wp = WaitingPod(pod, node_name, verdict, self.clock.now())
                    self._waiting[pod.key] = (
                        wp, info, cycle, state, t0, prep.step,
                    )
                    if self.journal is not None:
                        self.journal.record(
                            prep.step, cycle, pod, "permit_wait",
                            node=node_name, profile=profile,
                            reason=",".join(sorted(verdict)),
                            attempts=info.attempts,
                        )
                    continue
                if verdict is not None:  # (plugin name, Status) rejection
                    self._unreserve_all(state, pod, node_name)
                    res.unschedulable.append(pod.key)
                    self._requeue(info, cycle)
                    permit_why = (
                        f"permit plugin {verdict[0]} rejected: "
                        + "; ".join(verdict[1].reasons)
                    )
                    self._gang_note_fail(rd, pod, permit_why)
                    self._event(
                        pod, "FailedScheduling", permit_why,
                        type_="Warning", action="Permit",
                    )
                    if self.journal is not None:
                        self.journal.record(
                            prep.step, cycle, pod, "permit_rejected",
                            node=node_name, reason=permit_why,
                            profile=profile, attempts=info.attempts,
                        )
                    continue

                # approved: the binding cycle commits AFTER the lock drops
                # (schedule_batch's pending pass). Gang members STAGE
                # on their round instead — they commit atomically (or
                # release together) when the round resolves.
                entry = (state, info, pod, node_name, cycle, t0, prep.step)
                if rd is not None:
                    rd["staged"].append(entry)
                    rd["done"].add(pod.key)
                    gang_staged += 1
                else:
                    pending.append(entry)
                # keep the lazily-snapshotted preemption view in sync with
                # assumes made later in this batch, so a subsequent failing
                # pod's dry-run sees current node occupancy (the cache-backed
                # view already counts the assume; a later bind failure
                # forgets it, making this at worst conservative)
                if preempt_placed is not None:
                    preempt_placed.setdefault(int(a), []).append(pod)
        if preempt_ran:
            metrics.framework_extension_point_duration_seconds.labels(
                "PostFilter", "Success", profile
            ).observe(preempt_dt)
        if bind_dt:
            # reserve-phase time (binds now commit post-lock and report
            # under the Bind point from schedule_batch)
            metrics.framework_extension_point_duration_seconds.labels(
                "Reserve", "Success", profile
            ).observe(bind_dt)

        # per-profile attempt metrics (this group's own wall time)
        attempt_avg = (self.clock.perf() - gs) / max(len(infos), 1)
        # "scheduled" attempts = this group's approved bindings (upstream
        # observes at scheduling-cycle end; a later bind failure records
        # separately under the error paths, like the binding goroutine)
        n_sched = len(pending) - pending_before + gang_staged
        n_unsched = len(res.unschedulable) - unsched_before
        n_fail = len(res.bind_failures) - failures_before
        if n_sched:
            metrics.schedule_attempts_total.labels("scheduled", profile).inc(n_sched)
            metrics.scheduling_attempt_duration_seconds.labels(
                "scheduled", profile
            ).observe(attempt_avg)
        if n_unsched:
            metrics.schedule_attempts_total.labels("unschedulable", profile).inc(
                n_unsched
            )
        if n_fail:
            metrics.schedule_attempts_total.labels("error", profile).inc(n_fail)
        if self.telemetry is not None:
            # the locked assume/Reserve/Permit region after validation
            self.telemetry.add_stage("apply", self.clock.perf() - t_apply)
        return True

    def _fold_signature(self, static, slot_nodes) -> bytes:
        """Memo key for the out-of-tree fold: plugin identities, the
        class reps' contract-visible content (labels, annotations,
        namespace, requests — the fields class_key_extra folds into the
        class identity beyond what the in-tree mask already encodes),
        the input mask bytes, and the node OBJECT identities (the cache
        replaces Node objects on update, so any node change rotates the
        key)."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        for p in self.config.out_of_tree_plugins:
            h.update(str(id(p)).encode())
        for rep in static.reps:
            # every field the solver-path plugin contract allows a plugin
            # to read (framework/interface.py): labels, annotations, and
            # the in-tree spec fields — selectors, affinity, tolerations,
            # requests, ports, spread. The in-tree mask does NOT encode
            # all of these (e.g. a toleration on an untainted cluster),
            # so they hash explicitly.
            h.update(
                repr(
                    (
                        sorted(rep.labels.items()),
                        sorted(rep.annotations.items()),
                        rep.namespace,
                        sorted(rep.resource_request().items()),
                        sorted(rep.node_selector.items()),
                        rep.affinity,
                        rep.tolerations,
                        rep.host_ports(),
                        rep.topology_spread_constraints,
                    )
                ).encode()
            )
        h.update(static.mask.tobytes())
        for n in slot_nodes:
            h.update(str(id(n)).encode())
        return h.digest()

    def _event(
        self, obj, reason: str, note: str,
        type_: str = "Normal", action: str = "Scheduling",
    ) -> None:
        """Events recorder (SURVEY §6.5): the broadcaster the reference
        wires through EventsToRegister, collapsed to direct records on
        the state service (the [BOUNDARY] apiserver stand-in dedups)."""
        self.cluster.record_event(
            obj, reason, note, type_=type_, action=action,
            timestamp=self.clock.now(),
        )

    # -- Reserve / Permit / Bind extension points (host-side, around the
    # device solve — framework.go#RunReservePluginsReserve,
    # #RunPermitPlugins, #WaitOnPermit, #RunPreBindPlugins,
    # #RunPostBindPlugins) --

    def _unreserve_all(self, state, pod: Pod, node_name: str) -> None:
        """Roll back a reservation: out-of-tree Unreserve in reverse
        registration order (idempotent by contract), volume unreserve,
        forget the assumed pod."""
        for p in reversed(self.registry.reserve):
            p.unreserve(state, pod, node_name)
        self.volume_binder.unreserve(pod.key)
        self.claim_allocator.unreserve(pod.key)
        if self.fleet is not None:
            self.fleet.withdraw(pod.key)
        try:
            self.cache.forget_pod(pod.key)
        except Exception:
            pass

    def _run_permit(self, state, pod: Pod, node_name: str):
        """None = approved; {plugin: timeout} = wait; (plugin, Status) =
        rejected. A rejection short-circuits, like RunPermitPlugins."""
        waits: dict[str, float] = {}
        for p in self.registry.permit:
            st, timeout = p.permit(state, pod, node_name)
            if st.code == StatusCode.WAIT:
                waits[p.name()] = max(float(timeout), 0.0)
            elif not st.is_success:
                return (p.name(), st)
        return waits or None

    def _commit_binding(self, entry: tuple, res: BatchResult) -> None:
        """The binding cycle for one approved pod — PreBind (out-of-tree
        plugins, then volumebinding's BindPodVolumes) -> Bind (extender
        delegate or the binding subresource) -> PostBind. Runs WITHOUT
        the cluster lock held (the bind may cross a wire); cache/queue
        bookkeeping re-acquires it briefly. Any failure unreserves and
        requeues with backoff (the bindingCycle failure path).
        Returns True when the pod bound."""
        state, info, pod, node_name, cycle, t_start, step = entry
        try:
            for p in self.registry.pre_bind:
                st = p.pre_bind(state, pod, node_name)
                if not st.is_success:
                    raise _Rejected(
                        f"PreBind plugin {p.name()} rejected: "
                        + "; ".join(st.reasons)
                    )
            if pod.pvc_names:
                self.volume_binder.bind_pod_volumes(pod)
            if self._dra and pod.resource_claim_names:
                self.claim_allocator.bind_pod_claims(pod)
            binder = next(
                (
                    cl
                    for cl in self.extender_clients
                    if cl.is_binder and cl.is_interested(pod)
                ),
                None,
            )
            if binder is not None:
                # extender.go#Bind: the first interested binder extender
                # owns the binding subresource call (scope note: the
                # extender's own apiserver client carries its fence)
                binder.bind(pod, node_name)
            else:
                self.cluster.bind(
                    pod.namespace, pod.name, node_name,
                    fence=(
                        (self._fence_role, self._fence_token)
                        if self._fence_role is not None
                        else None
                    ),
                )
        except (ApiError, VolumeBindingError, _Rejected, ExtenderError) as e:
            reason = e.reason if isinstance(e, ApiError) else str(e)
            fenced = isinstance(e, ApiError) and e.fenced
            with self.cluster.lock:
                if fenced:
                    # this incarnation's fence token was revoked (lease
                    # lost / partition / superseded): the state service
                    # refused the commit — the zombie path the fence
                    # exists to close. The pod requeues like any bind
                    # conflict; the operator signal is the counter+log
                    # (production wires reacquire_fence to lease
                    # re-acquisition before commits can resume).
                    metrics.commit_fenced_total.inc()
                    self._fenced_commits += 1
                    self._log.warning(
                        "bind of %s fenced: this incarnation's commit "
                        "fence (role %r) was revoked — operating as a "
                        "zombie until the lease is re-acquired",
                        pod.key, self._fence_role,
                        extra={"step": step},
                    )
                self._unreserve_all(state, pod, node_name)
                res.bind_failures.append((pod.key, reason))
                if self.journal is not None:
                    self.journal.record(
                        step, cycle, pod, "bind_failure",
                        node=node_name, reason=reason,
                        attempts=info.attempts,
                    )
                try:
                    self.cluster.get_pod(pod.namespace, pod.name)
                except ApiError:
                    # deleted while the bind was in flight (the unlocked
                    # window): don't requeue a pod that no longer exists
                    return False
                self._requeue(info, cycle)
                self._event(
                    pod, "FailedScheduling",
                    f"binding rejected: {reason}", type_="Warning",
                    action="Binding",
                )
            return False
        with self.cluster.lock:
            self.cache.finish_binding(pod.key)
            self.volume_binder.finish(pod.key)
            self.claim_allocator.finish(pod.key)
            if self.fleet is not None:
                # pending -> committed on the exchange: the row now
                # represents durable occupancy peers must respect
                # until the pod is deleted
                self.fleet.commit(pod.key)
            self._event(
                pod, "Scheduled",
                f"Successfully assigned {pod.key} to {node_name}",
                action="Binding",
            )
            res.scheduled.append((pod.key, node_name))
            if self.journal is not None:
                self.journal.record(
                    step, cycle, pod, "bound",
                    node=node_name, attempts=info.attempts,
                )
        res.latencies.append(self.clock.perf() - t_start)
        # pod-level SLIs: attempts-to-success histogram and e2e latency
        # from first queue entry, labeled by attempt count
        e2e = max(self.clock.now() - info.initial_attempt_timestamp, 0.0)
        res.e2e_latencies.append(e2e)
        metrics.pod_scheduling_attempts.observe(info.attempts)
        metrics.pod_scheduling_sli_duration_seconds.labels(
            str(min(info.attempts, 16))
        ).observe(e2e)
        for p in self.registry.post_bind:
            p.post_bind(state, pod, node_name)
        with self.cluster.lock:
            self._in_flight.pop(pod.key, None)
        return True

    # called only from _schedule_cycle's locked region: ktpu: holds(cluster.lock)
    def _process_waiting(self, res: BatchResult, pending: list) -> None:
        """Settle WaitingPods (the batched WaitOnPermit): rejected or
        timed-out pods unreserve and requeue; fully-allowed pods complete
        their binding cycle in the post-lock pending pass."""
        now = self.clock.now()
        for key, (wp, info, cycle, state, t_start, step) in list(
            self._waiting.items()
        ):
            expired = wp.expired(now)
            if wp.rejected_by is not None or expired is not None:
                del self._waiting[key]
                self._unreserve_all(state, wp.pod, wp.node_name)
                res.unschedulable.append(key)
                self._requeue(info, cycle)
                why = (
                    f"permit plugin {wp.rejected_by} rejected: "
                    f"{wp.reject_message}"
                    if wp.rejected_by is not None
                    else f"permit plugin {expired} timed out"
                )
                self._event(
                    wp.pod, "FailedScheduling", why,
                    type_="Warning", action="Permit",
                )
                if self.journal is not None:
                    self.journal.record(
                        step, cycle, wp.pod,
                        "permit_rejected"
                        if wp.rejected_by is not None
                        else "permit_timeout",
                        node=wp.node_name, reason=why,
                        attempts=info.attempts,
                    )
            elif wp.allowed:
                del self._waiting[key]
                # back under the in-flight fence until the bind commits:
                # a MODIFIED event during the unlocked windows must not
                # re-enqueue a pod that is about to bind (review-caught)
                self._in_flight[key] = info
                pending.append(
                    (state, info, wp.pod, wp.node_name, cycle, t_start,
                     step)
                )

    def waiting_pods(self) -> dict[str, WaitingPod]:
        """GetWaitingPod/IterateOverWaitingPods surface: pod key ->
        WaitingPod; call .allow(plugin)/.reject(plugin, msg) on entries —
        verdicts apply at the start of the next scheduling cycle."""
        return {k: entry[0] for k, entry in self._waiting.items()}

    def _run_post_filter(self, pod: Pod, filtered: dict) -> str | None:
        """Out-of-tree PostFilter plugins, after default preemption found
        nothing: first success nominates (schedule_one.go's PostFilter
        loop semantics)."""
        state = CycleState()
        for p in self.registry.post_filter:
            node_name, st = p.post_filter(state, pod, filtered)
            if st.code == StatusCode.ERROR:
                raise RuntimeError(
                    f"PostFilter plugin {p.name()} error: {st.reasons}"
                )
            if st.is_success and node_name:
                try:
                    self.cluster.patch_pod_status(
                        pod.namespace, pod.name,
                        nominated_node_name=node_name,
                    )
                except ApiError:
                    return None
                pod.nominated_node_name = node_name
                return node_name
        return None

    def _record_metrics(
        self,
        res: BatchResult,
        n_pods: int,
        occ_sensitive: bool = False,
    ) -> None:
        """Batch-level metrics (per-profile attempt counters record in
        _solve_group); reference names, SURVEY §6.5. Also the tuning
        tick: every dispatch loop (sync, pipelined, streaming, drain)
        funnels applied batches through here, so this is where the
        auto-tuning runtime samples its CounterWindow and drives the
        per-knob controllers — one chokepoint, no loop grows its own
        tuning call."""
        metrics.solve_latency_seconds.observe(res.solve_seconds)
        metrics.solve_batch_size.observe(n_pods)
        for _, _, victims in res.preemptions:
            metrics.preemption_attempts_total.inc()
            metrics.preemption_victims.observe(len(victims))
        self._refresh_pending_gauge()
        if self.tuner is not None and n_pods > 0:
            self.tuner.observe_batch(
                self, res, n_pods, occ_sensitive=occ_sensitive
            )

    def _refresh_pending_gauge(self) -> None:
        """Set the pending_pods gauge from the queue's O(1) counters —
        called wherever queue contents change (watch ingest, pops,
        requeues, discards), not just the solve-recording path, so the
        gauge cannot go stale on idle cycles or queue-only
        transitions."""
        for queue_name, count in self.queue.pending_counts().items():
            self._pending_gauges[queue_name].set(count)

    # -- PostFilter: defaultpreemption (preemption.go#Evaluator.Preempt) --

    def _placed_by_slot(self) -> dict[int, list[Pod]]:
        out: dict[int, list[Pod]] = {}
        for slot, name in enumerate(self.snapshot.names):
            ninfo = self.cache.nodes.get(name) if name else None
            if ninfo is not None and ninfo.node is not None and ninfo.pods:
                out[slot] = list(ninfo.pods.values())
        return out

    def _try_preempt(
        self,
        pod: Pod,
        static,
        idx: int,
        res: BatchResult,
        placed_by_slot: dict[int, list[Pod]],
        slot_nodes: list | None,
        pdbs: list,
        cluster_has_affinity: bool,
        solver: ExactSolver,
        dra_prefold: dict | None = None,
    ) -> str | None:
        if pod.preemption_policy == "Never":
            return None
        prio = pod.effective_priority
        # cheap pre-check: any lower-priority pod anywhere?
        if not any(
            q.effective_priority < prio
            for placed in placed_by_slot.values()
            for q in placed
        ):
            return None

        batch = self.snapshot.batch
        static_row = static.mask[static.class_of[idx]]
        # DRA device exhaustion is preemptible (upstream dynamicresources
        # Filter returns Unschedulable, not Unresolvable): widen candidate
        # selection to the pre-DRA mask; a chosen node that the DRA fold
        # had excluded must pass the victims-release recheck below
        widen_row = None
        if dra_prefold and pod.resource_claim_names:
            widen_row = dra_prefold.get(int(static.class_of[idx]))
        # the pod's failure can involve beyond-fit filters when it carries
        # ports/spread constraints or pod (anti-)affinity is in play — then
        # the dry-run must re-run the full pipeline per candidate/re-add
        beyond_fit = bool(
            pod.host_ports()
            or pod.topology_spread_constraints
            or (
                pod.affinity is not None
                and (
                    pod.affinity.pod_affinity is not None
                    or pod.affinity.pod_anti_affinity is not None
                )
            )
            or cluster_has_affinity
        )
        result = self.preemptor.evaluate(
            pod, batch, self.snapshot.names, placed_by_slot,
            widen_row if widen_row is not None else static_row,
            pdbs,
            slot_nodes=slot_nodes, beyond_fit=beyond_fit,
            disabled=frozenset(solver.config.disabled_filters),
        )
        if widen_row is not None:
            # DRA path: the resource-driven dry-run doesn't model devices,
            # so its victim set (possibly empty) may not free any. Validate
            # it; when it doesn't hold up, select device-holding victims
            # directly (lowest priority first, PDB-respecting).
            ok = False
            if result is not None:
                try:
                    slot = self.snapshot.slot_of(result.node_name)
                except KeyError:
                    return None
                ok = bool(static_row[slot]) or (
                    bool(result.victims)
                    and self._dra_preempt_ok(
                        pod, result.node_name, result.victims
                    )
                )
            if not ok:
                # retry the UNWIDENED mask (a resource-only preemption on
                # a DRA-feasible node needs no device math) — but only
                # when the widened run FOUND something its recheck
                # rejected: static_row is a subset of widen_row, so a
                # widened None is already a subset None
                if result is not None:
                    result = self.preemptor.evaluate(
                        pod, batch, self.snapshot.names, placed_by_slot,
                        static_row, pdbs,
                        slot_nodes=slot_nodes, beyond_fit=beyond_fit,
                        disabled=frozenset(solver.config.disabled_filters),
                    )
                if result is None:
                    result = self._dra_victim_preempt(
                        pod, prio, placed_by_slot, widen_row, pdbs,
                        beyond_fit=beyond_fit, slot_nodes=slot_nodes,
                        disabled=frozenset(solver.config.disabled_filters),
                    )
        if result is None:
            return None
        # prepareCandidate: API-delete victims; clear lower-priority
        # nominations on the node; set our nominatedNodeName. Keep the
        # shared placed_by_slot in sync so later pods in this batch see the
        # evictions (the cache also updates via the DELETED watch events).
        victim_keys = {v.key for v in result.victims}
        for victim in result.victims:
            self._event(
                victim, "Preempted",
                f"Preempted by {pod.key} on node {result.node_name}",
                type_="Warning", action="Preempting",
            )
            try:
                self.cluster.delete_pod(victim.namespace, victim.name)
            except ApiError:
                pass  # already gone — fine
        for slot, placed in list(placed_by_slot.items()):
            remaining = [q for q in placed if q.key not in victim_keys]
            if len(remaining) != len(placed):
                if remaining:
                    placed_by_slot[slot] = remaining
                else:
                    del placed_by_slot[slot]
        for other in self.cluster.list_pods():
            if (
                not other.node_name
                and other.nominated_node_name == result.node_name
                and other.effective_priority < prio
            ):
                self.cluster.patch_pod_status(
                    other.namespace, other.name, nominated_node_name=""
                )
        try:
            self.cluster.patch_pod_status(
                pod.namespace, pod.name, nominated_node_name=result.node_name
            )
        except ApiError:
            return None  # pod vanished mid-preemption
        pod.nominated_node_name = result.node_name
        self._event(
            pod, "Nominated",
            f"preemption made room on {result.node_name}: nominated "
            f"({len(result.victims)} victim(s) evicted)",
            action="Preempting",
        )
        res.preemptions.append(
            (pod.key, result.node_name, [v.key for v in result.victims])
        )
        return result.node_name

    def _dra_victim_preempt(
        self,
        pod: Pod,
        prio: int,
        placed_by_slot: dict[int, list[Pod]],
        widen_row: np.ndarray,
        pdbs: list,
        beyond_fit: bool = False,
        slot_nodes: list | None = None,
        disabled: frozenset = frozenset(),
    ):
        """Device-driven victim selection for claim-bearing preemptors:
        per candidate node, evict the least-important claim-holding pods
        (PDB-respecting, never PDB-violating) until the pod's claims would
        allocate, and verify the pod still passes the filters with the
        victims gone (resources always; the full scalar pipeline when the
        pod/cluster carries beyond-fit constraints). Chooses the candidate
        needing the fewest victims (tie: node name) — the leading keys of
        pickOneNodeForPreemption."""
        from .ops.oracle.noderesources import fit_filter
        from .ops.oracle.preemption import classify_pdb_violations
        from .ops.oracle.profile import FullOracle, make_oracle_nodes
        from .solver.preemption import PreemptionResult

        ctx = self.claim_allocator.context()
        best: PreemptionResult | None = None
        for slot, resident in placed_by_slot.items():
            if slot >= len(widen_row) or not widen_row[slot]:
                continue
            node_name = self.snapshot.names[slot]
            info = self.cache.nodes.get(node_name)
            if info is None or info.node is None:
                continue
            lower = [q for q in resident if q.effective_priority < prio]
            _viol, safe = classify_pdb_violations(lower, pdbs)
            # claim-holding pods only, least important first
            holders = [
                q
                for q in sorted(
                    safe,
                    key=lambda q: (q.effective_priority, -q.start_time),
                )
                if any(
                    (c := ctx.claims.get(f"{q.namespace}/{n}")) is not None
                    and c.allocated_node == node_name
                    for n in q.resource_claim_names
                )
            ]
            victims: list[Pod] = []
            for q in holders:
                victims.append(q)
                if self._dra_preempt_ok(pod, node_name, victims):
                    break
            else:
                continue  # exhausted holders without freeing enough
            victim_keys = {v.key for v in victims}
            remaining = [q for q in resident if q.key not in victim_keys]
            if beyond_fit:
                # ports/spread/interpod/volume filters need the whole
                # cluster's occupancy (minus the victims) — a resource-only
                # check could evict victims on a node the pod still can't
                # land on (review-caught)
                live = [
                    (s2, n2)
                    for s2, n2 in enumerate(slot_nodes or [])
                    if n2 is not None
                ]
                by_name = {
                    n2.name: (
                        remaining
                        if n2.name == node_name
                        else placed_by_slot.get(s2, [])
                    )
                    for s2, n2 in live
                }
                oracle = FullOracle(
                    make_oracle_nodes([n2 for _, n2 in live], by_name),
                    disabled=disabled,
                )
                target = next(
                    on for on in oracle.nodes if on.node.name == node_name
                )
                if not oracle.filter_one(pod, target):
                    continue
            else:
                on = make_oracle_nodes(
                    [info.node], {node_name: remaining}
                )[0]
                if fit_filter(pod, on.res):
                    continue
            if best is None or (len(victims), node_name) < (
                len(best.victims), best.node_name
            ):
                best = PreemptionResult(
                    node_name=node_name, victims=victims, num_violating=0
                )
        return best

    def _dra_preempt_ok(self, pod: Pod, node_name: str, victims) -> bool:
        """Would evicting ``victims`` free enough claim devices on
        ``node_name`` for ``pod``'s claims? Simulates the deallocating
        controller's release (claims reserved exclusively by victims lose
        their allocation) on a copy of the claim context, then re-runs the
        greedy pick."""
        from .ops.oracle.dra import ClaimError

        ctx = self.claim_allocator.context()
        victim_keys = {v.key for v in victims}
        freed = set(ctx.taken.get(node_name, ()))
        claims = dict(ctx.claims)
        changed = False
        for key, c in list(claims.items()):
            if (
                c.allocated
                and c.allocated_node == node_name
                and c.reserved_for
                and all(k in victim_keys for k in c.reserved_for)
            ):
                for r in c.results:
                    freed.discard((r.driver, r.pool, r.device))
                from .api.dra import ResourceClaim

                claims[key] = ResourceClaim(
                    name=c.name,
                    namespace=c.namespace,
                    requests=c.requests,
                )
                changed = True
        if not changed:
            return False
        ctx.claims = claims
        ctx.taken = dict(ctx.taken)
        ctx.taken[node_name] = freed
        try:
            # resolves through the mutated ctx.claims, so released claims
            # are already the unallocated copies
            pod_claims = ctx.pod_claims(pod)
        except ClaimError:
            return False
        return ctx.pick(node_name, pod_claims) is not None

    def run_until_settled(self, max_batches: int = 10_000) -> list[BatchResult]:
        """Drain the active queue (benchmark / test driver)."""
        out = []
        for _ in range(max_batches):
            r = self.schedule_batch()
            if not r.progressed:
                break
            out.append(r)
        return out

    # -- double-buffered loop (VERDICT r4 #1) --

    def _plain_batch(self, pods: list[Pod]) -> bool:
        """True when tensorizing this batch reads NO host state that a
        previous batch's apply could change — exactly then it may be
        prepared and dispatched before the previous solve's results land
        (the device session carries the fit/balanced node state forward
        on its own). Ports/spread/interpod occupancy, volume and DRA
        context, and nominated-pod load are all rebuilt from the cache
        each batch, so any of them routes to the pipelined CARRY mode
        instead: drain in-flight solves before tensorizing, then overlap
        via the chained sub-batch split (run_pipelined)."""
        if self.nominated_pods or self._waiting:
            return False
        for p in pods:
            if p.host_ports() or p.topology_spread_constraints or p.pvc_names:
                return False
            if p.affinity is not None and (
                p.affinity.pod_affinity is not None
                or p.affinity.pod_anti_affinity is not None
            ):
                return False
            if self._dra and (
                p.resource_claim_names or p.claim_templates_unresolved
            ):
                return False
        if any(
            info.pods_with_affinity
            for info in self.cache.nodes.values()
            if info.node is not None
        ):
            return False
        if self.solver.config.spread_defaulting == "System":
            services = self.cluster.list_services()
            if services:
                from .ops.oracle.spread import default_selector

                if any(
                    not p.topology_spread_constraints
                    and default_selector(p, services) is not None
                    for p in pods
                ):
                    return False
        return True

    def _stream_chainable(self, pods: list[Pod]) -> bool:
        """Cross-batch chain eligibility (run_streaming): the device
        stream carry holds fit + port/spread/interpod occupancy rows —
        exactly those shapes may chain over an undrained ring. Volume
        and DRA feasibility are folded HOST-side at tensorize and are
        NOT in the carry, so a batch bearing them must drain first or
        it would solve against attach/device availability that misses
        the ring's pending placements (each such pod would then fail
        Reserve and requeue-churn)."""
        for p in pods:
            if p.pvc_names:
                return False
            if self._dra and (
                p.resource_claim_names or p.claim_templates_unresolved
            ):
                return False
        return True

    def _note_drain_chunk(self, step: int) -> None:
        """While a backlog drain is active, point the journal's
        drain_chunk tag at the chunk (trace step) whose records are
        about to be written. Derived PER CALL SITE — apply, discard,
        solver failure, quarantine — so failure-path records attribute
        to THEIR chunk, not whichever flight last applied (with a full
        stream ring those differ by up to stream_depth chunks). Driver
        thread only; drain_backlog pops the tag when the pass ends."""
        if self._backlog_drain_active and self.journal is not None:
            self.journal.tags["drain_chunk"] = (
                step - self._drain_chunk_base
            )

    def _discard_flight(self, flight: _InFlightSolve) -> None:
        """Drop a stale (or salvaged) deferred solve. The pods retry at
        the head of the active queue with no backoff (the failure is the
        solve's, not theirs) — EXCEPT pods that were externally bound or
        deleted mid-flight (often the very event that tripped the fence):
        requeueing those would create ghost entries that churn forever
        (review-caught). The device session's carried state counted the
        discarded placements, so it is marked stale and re-uploads from
        host truth once the pipeline has drained (a later solve may still
        be chained on it)."""
        metrics.solves_discarded_total.inc()
        prep = flight.prep
        if self.telemetry is not None:
            # fence-wait attribution: the discarded flight's dispatch +
            # read wall was work the fence threw away, and its capture
            # record can never complete
            self.telemetry.add_stage(
                "fence_wait",
                flight.dispatch_seconds + (flight.read_seconds or 0.0),
            )
            if self.telemetry.bundles is not None:
                self.telemetry.bundles.drop(prep.step)
        self._note_drain_chunk(prep.step)
        if prep.step != self._last_discard_step:
            self._discard_streak += 1
            self._last_discard_step = prep.step
        infos = flight.infos()
        with self.cluster.lock, self.obs.span(
            "fence", trace_id=prep.step, action="discard",
            pods=len(infos), fence=prep.fence,
        ):
            self._session_stale.add(prep.profile)
            if self._gang is not None and self._gang_rounds:
                # a discarded flight can never resolve its gang rounds:
                # staged siblings from earlier flights of the same
                # batch release + requeue here (this flight's own
                # members were never staged — they requeue below)
                self._release_gang_rounds_for(
                    {i.key for i in infos},
                    "gang member's solve was discarded",
                )
            for info in infos:
                self._in_flight.pop(info.key, None)
                if self.journal is not None:
                    self.journal.record(
                        prep.step, prep.base_cycle, info.pod, "discarded",
                        profile=prep.profile, attempts=info.attempts,
                    )
                try:
                    cur = self.cluster.get_pod(
                        info.pod.namespace, info.pod.name
                    )
                except ApiError:
                    continue  # deleted while the solve was in flight
                if cur.node_name:
                    continue  # bound externally while in flight
                info.pod = cur
                self.queue.requeue_popped(info)
            self._refresh_pending_gauge()

    # per-batch apply path: device reads only through the sanctioned
    # _InFlightSolve.assignments boundary: ktpu: hot
    def _apply_flight(self, flight: _InFlightSolve) -> BatchResult:
        """Apply (or discard) a deferred solve and commit its bindings."""
        res = BatchResult()
        pending: list = []
        prep = flight.prep
        infos = flight.infos()
        self._note_drain_chunk(prep.step)
        # ktpu: ignore[LOCK001]: deliberately unlocked pre-check — a torn read can only misroute to the locked re-check inside _apply_group or to a discard, both safe
        fence_fresh = prep.fence == self._conflict_seq
        # ktpu: ignore[LOCK001]: same deliberately unlocked pre-check; the locked re-check inside _apply_group is authoritative
        occ_fresh = not prep.occ_sensitive or prep.occ_fence == self._occupancy_seq
        if fence_fresh and occ_fresh:
            applied = False
            ta = self.clock.perf()
            try:
                # the fence is re-checked INSIDE _apply_group's locked
                # region: a conflicting event can land during the device
                # read (review-caught check-to-lock window)
                applied = self._apply_group(
                    flight, res, pending, fence=prep.fence
                )
                self._note_flight_timing(flight, len(infos))
                # RTT attribution (ladder #6): a deferred read that
                # blocked the driver > 1 ms paid an un-hidden tunnel
                # round trip; anything faster was hidden by overlapped
                # host work / the completion thread's pre-wait. The
                # threshold makes this deterministic under FakeClock
                # (virtual reads never block).
                if isinstance(flight.handle, DeferredAssignments):
                    if flight.read_seconds > 1e-3:
                        self._reads_paid += 1
                        if self._streaming_active:
                            metrics.stream_unhidden_reads_total.inc()
                    else:
                        self._reads_hidden += 1
                if applied:
                    # host cost = this batch's own tensorize + apply
                    # phases; wall-since-pop would charge the overlapped
                    # batches' work and the hidden RTT to this batch
                    # (review-caught). Chained sub-flights report the
                    # shared tensorize cost on the first flight only.
                    tshare = (
                        prep.tensorize_seconds
                        if flight.tensorize_share is None
                        else flight.tensorize_share
                    )
                    res.host_seconds = tshare + (
                        self.clock.perf() - ta - flight.read_seconds
                    )
                    self._record_metrics(
                        res, len(infos),
                        occ_sensitive=prep.occ_sensitive,
                    )
            except SolverFaultError as e:
                # the solve is the failure (read death / corrupt
                # output), not the fence: requeue the pods for an
                # immediate retry and route it through the synchronous
                # resilient path, where the fallback ladder owns it.
                # Raised pre-mutation, so the discard is clean.
                self.resilience.note_async_failure(prep.profile)
                self._solver_failed(
                    infos, e, None, prep.step, prep.base_cycle
                )
                self._discard_flight(flight)
                res.completed_at = self.clock.perf()
                return res
            except Exception:
                # the fence matched, so _apply_group may have read the
                # device assignments before dying: the session's carried
                # state counts this batch's placements, but the requeued
                # pods never bound. Mark the carry stale so the next
                # dispatch re-uploads from host truth instead of counting
                # phantom placements against future solves (ADVICE r5 #3)
                with self.cluster.lock:
                    self._session_stale.add(prep.profile)
                self._requeue_unhandled(infos, pending, res)
                self._commit_all(infos, pending, res)
                raise
            if applied:
                # forward progress: reset the backstop (and the
                # within-chain discard dedup)
                self._discard_streak = 0
                self._last_discard_step = -1
                self._commit_all(infos, pending, res)
                if self._backlog_drain_active and self.fleet is not None:
                    # fleet drain: the per-chunk progress report feeds
                    # the hub's lease ledger AND refreshes this
                    # replica's liveness stamp — a replica deep in a
                    # long drain writes nothing else to the hub, and
                    # without the touch it would age past max_row_age_s
                    # and flip every peer conservative
                    self.fleet.drain_chunk_progress(
                        [k for k, _ in res.scheduled]
                    )
                res.completed_at = self.clock.perf()
                return res
        self._discard_flight(flight)
        res.completed_at = self.clock.perf()
        return res

    def _note_flight_timing(self, flight: _InFlightSolve, n_pods: int) -> None:
        """Feed the adaptive batch-split estimators — which live in the
        shared CounterWindow (kubernetes_tpu/tuning), the one home of
        every estimate a knob decision reads — from an applied (or
        read-then-discarded) flight. Driver thread only."""
        self.window.note_read(
            flight.read_seconds, flight.dispatch_seconds, n_pods
        )

    _MAX_PIPELINE_SPLIT = 8

    def _choose_split(self, n_pods: int) -> int:
        """Sub-batch count for one popped batch (the RTT-hiding batch
        split). A fixed config wins; with the tuning runtime governing
        the knob, its hill-climb controller owns the value outright;
        otherwise the adaptive default (CounterWindow.split_estimate)
        splits once the estimated device solve time for the batch
        exceeds the estimated read round trip, so the assignment read
        of sub-batch i can overlap the solve of i+1 — the knob that
        attacks the per-batch RTT floor. Controller and adaptive rule
        read the SAME window, so the two can never fight over the split
        from divergent private estimates (ISSUE 13 satellite). The
        solver clamps the request to a feasible (group-aligned) divisor
        of the padded pod axis."""
        cfg = self.config.pipeline_split
        if cfg == 1:
            return 1
        if cfg > 1:
            return min(cfg, self._MAX_PIPELINE_SPLIT)
        if self.tuner is not None:
            tuned = self.tuner.split_override(n_pods)
            if tuned is not None:
                return min(max(tuned, 1), self._MAX_PIPELINE_SPLIT)
        return self.window.split_estimate(
            n_pods, self._MAX_PIPELINE_SPLIT
        )

    def run_pipelined(self, max_batches: int = 10_000) -> list[BatchResult]:
        """Drain the queue with deferred solves in flight: host work for
        the NEXT dispatch overlaps the device→host tunnel round trip of
        solves already dispatched, so steady-state throughput pays host
        work, not round trips (VERDICT r4 #1; the reference's
        scheduleOne overlaps binding the same way —
        schedule_one.go#scheduleOne's bind goroutine [U] — extended here
        to the device boundary). Every popped batch takes one of three
        modes (scheduler_pipeline_mode_total):

        - **overlap**: _plain_batch shapes — batch k+1 is tensorized and
          dispatched BEFORE batch k's assignments land (the device
          session carries fit state forward, so k+1's solve already sees
          k's placements). Extender / out-of-tree Filter+Score folding
          is a pre-dispatch host stage here: verdicts fold into the
          class tables per batch and read nothing a previous apply
          writes, so they ride the overlap instead of forcing the
          synchronous loop.
        - **carry**: hard shapes (ports/spread/interpod, volumes, DRA,
          nominated pods) and multi-profile sub-batches — in-flight
          solves drain FIRST so tensorization reads exact occupancy,
          then the batch dispatches as up to K chained sub-solves whose
          occupancy rows stay device-resident between them
          (BatchCarriedUsage): the assignment read of sub-batch i
          overlaps the solve of i+1, and each sub-batch's apply/bind
          work overlaps the next sub-batch's solve. Only the final read
          pays an un-hidden RTT per popped batch.
        - **sync**: the livelock backstop (below) and WaitingPod
          settlement, via the fence-free synchronous cycle.

        Safety: every dispatched solve is fenced on _conflict_seq, and
        occupancy-sensitive solves additionally on _occupancy_seq
        (assigned-pod deletes/label re-keys, external DRA claim writes —
        the event kinds whose effects the carried state cannot absorb).
        A conflicting event between dispatch and apply discards the
        solve, resets the device session, and requeues the pods for an
        immediate retry.

        Livelock backstop (ADVICE r5 #2): _PIPELINE_FALLBACK_AFTER
        consecutive fence discards force one synchronous (fence-free)
        cycle — counted by scheduler_pipeline_fallback_total — so
        sustained capacity/mask event churn degrades to the synchronous
        path's throughput instead of zero forward progress."""
        out: list[BatchResult] = []
        flights: list[_InFlightSolve] = []

        def apply_one() -> None:
            f = flights.pop(0)
            r = self._apply_flight(f)
            if r.progressed:
                out.append(r)

        def drain() -> None:
            while flights:
                apply_one()

        batches = 0
        try:
            while batches < max_batches:
                if self.fleet is not None and self.fleet.maybe_resync(
                    self
                ):
                    # the partition moved: in-flight solves are fenced
                    # stale (resync bumped both fences) — drain so
                    # they discard before the next shard-scoped pop
                    drain()
                if self._waiting:
                    drain()
                    # WaitingPod settlement is a synchronous cycle: it
                    # counts under mode="sync" like the backstop does
                    metrics.pipeline_mode_total.labels("sync").inc()
                    r = self.schedule_batch()
                    batches += 1
                    if not r.progressed:
                        break
                    out.append(r)
                    continue
                t0 = self.clock.perf()
                with self.cluster.lock:
                    self._release_quarantine()
                    self._reap_expired_assumes()
                    self.queue.flush_unschedulable_leftover()
                    infos = self.queue.pop_batch(self.config.batch_size)
                    for i in infos:
                        self._in_flight[i.key] = i
                    if self._gang is not None:
                        # gang gate BEFORE base_cycle: the gate moves
                        # pods in and out of the batch, and base_cycle
                        # must describe the batch that actually runs
                        infos = self._gang_gate(infos)
                    base_cycle = self.queue.scheduling_cycle - len(infos)
                    plain = bool(infos) and self._plain_batch(
                        [i.pod for i in infos]
                    )
                    self._refresh_pending_gauge()
                if not infos:
                    if flights:
                        drain()
                        continue  # discards/failures may requeue work
                    if self.rebalancer is not None:
                        # idle + pipeline drained: the one safe point
                        # for a rebalance pass in this loop (no
                        # in-flight solve can go stale on the eviction
                        # events). Evictions re-populate the queue, so
                        # loop back and schedule the migrations.
                        r = BatchResult()
                        if self.rebalancer.maybe_run(self, r) > 0:
                            r.completed_at = self.clock.perf()
                            out.append(r)
                            continue
                    break
                batches += 1
                # batch id for this pop's spans/journal (the sync branch
                # below re-enters via _run_popped, not schedule_batch)
                self._trace_step += 1
                if self.resilience.should_sync():
                    # degraded mode (kubernetes_tpu/resilience): a
                    # ladder tier is tripped or probing, an async solve
                    # failure is pending, or the ladder is pinned.
                    # Deferred dispatch assumes the healthy top tier,
                    # so the batch routes through the synchronous
                    # resilient cycle, which owns rebuilds, tier
                    # descent, probes, and quarantine.
                    metrics.pipeline_mode_total.labels("sync").inc()
                    drain()
                    r = self._run_popped(infos, t0)
                    if r.progressed:
                        out.append(r)
                    continue
                if self._discard_streak >= self._PIPELINE_FALLBACK_AFTER:
                    # livelock backstop (ADVICE r5 #2): N consecutive
                    # fence discards mean conflicting events are landing
                    # faster than one per dispatch→apply window, and the
                    # fenced pipeline can requeue forever with zero
                    # forward progress. One synchronous cycle applies
                    # WITHOUT a fence (accepting the same solve-window
                    # staleness the reference's binding goroutines do),
                    # guaranteeing at least one batch lands per N
                    # discards under sustained churn.
                    metrics.pipeline_fallback_total.inc()
                    metrics.pipeline_mode_total.labels("sync").inc()
                    self._log.warning(
                        "pipeline livelock backstop engaged after %d "
                        "consecutive fence discards: one synchronous "
                        "cycle", self._discard_streak,
                        extra={"step": self._trace_step},
                    )
                    drain()
                    r = self._run_popped(infos, t0)
                    # the synchronous cycle applied (no fence): the
                    # backstop counter restarts from real progress
                    self._discard_streak = 0
                    self._last_discard_step = -1
                    if r.progressed:
                        out.append(r)
                    continue
                # profile sub-batches in pop order (multi-profile configs
                # pipeline per group; single-profile is one group)
                groups = self._group_by_profile(infos)
                overlap_ok = plain and len(groups) == 1
                metrics.pipeline_mode_total.labels(
                    "overlap" if overlap_ok else "carry"
                ).inc()
                # ``owned``: popped groups not yet handed to a flight —
                # an exception below must requeue exactly these (handing
                # off removes a group; review-caught leak)
                owned: list[list[QueuedPodInfo]] = [g[1] for g in groups]
                try:
                    for profile, group_infos, offsets in groups:
                        self._pipeline_group(
                            profile, group_infos, offsets, base_cycle,
                            t0, overlap_ok, flights, apply_one, drain,
                            owned,
                        )
                except Exception:
                    if owned:
                        with self.cluster.lock:
                            base = self.queue.scheduling_cycle
                            for group_infos in owned:
                                for info in group_infos:
                                    self._requeue(info, base)
                    raise
            drain()
        except Exception:
            # the crash trigger for the pipelined loop (the synchronous
            # loop dumps from schedule_batch)
            if self.flight is not None:
                path = self.flight.dump(trigger="crash")
                self._log.exception(
                    "pipelined loop failed; flight recorder dump: %s",
                    path, extra={"step": self._trace_step},
                )
            raise
        finally:
            # exception escape hatch: dispatched-but-unapplied solves
            # must not strand their pods in _in_flight nor leave the
            # device session silently ahead of host truth (review-caught)
            for f in flights:
                self._discard_flight(f)
            flights.clear()
        return out

    def _pipeline_group(
        self,
        profile: str,
        infos: list[QueuedPodInfo],
        cycle_offsets: list[int],
        base_cycle: int,
        t0: float,
        overlap_ok: bool,
        flights: list,
        apply_one,
        drain,
        owned: list,
    ) -> None:
        """Tensorize, fold, and dispatch one profile group through the
        pipeline, leaving its LAST sub-flight in ``flights`` so the next
        pop/tensorize overlaps its read. Carry-mode groups (overlap_ok
        False) drain first: their occupancy tensors and volume/claim
        contexts must see every prior apply — the RTT hiding then comes
        from the chained sub-batch split and from each sub-batch's
        apply/bind work overlapping its successor's solve."""
        if not overlap_ok:
            drain()
        elif flights:
            with self.cluster.lock:
                stale = bool(self._session_stale)
            if stale or flights[0].prep.profile != profile:
                # drain before dispatch when (a) the last apply
                # discarded a solve — the stale device carry must
                # re-upload at dispatch — or (b) the in-flight solve
                # belongs to ANOTHER profile: its placements live only
                # in that profile's session carry, so this profile's
                # tensorize/session would double-book the capacity it
                # claimed (multi-profile configs overlap only
                # same-profile consecutive batches)
                drain()
        prep = self._tensorize_group(
            profile, infos, cycle_offsets, base_cycle, t0
        )
        with self.obs.span(
            "fold", trace_id=prep.step, profile=profile,
            extenders=len(self.extender_clients),
            plugins=len(self.config.out_of_tree_plugins),
        ):
            # extender / out-of-tree / DRA folding as a pre-dispatch
            # host stage: pure per (class, node) by contract, so it
            # overlaps an in-flight solve's tunnel RTT
            self._fold_group(prep)
        if flights and prep.fence != flights[0].prep.fence:
            # an event landed since the in-flight solve's snapshot. The
            # deferred heal (allow_heal=False) is only conservative for
            # USAGE columns — node TABLES (allocatable/valid) can
            # shrink, and a solve against stale tables would carry THIS
            # prep's fresh fence and apply a capacity violation
            # (review-caught). Drain first: the stale flight discards
            # itself, and this dispatch heals with current tables.
            drain()
        split = self._choose_split(len(infos))
        try:
            try:
                new = self._dispatch(
                    prep, allow_heal=not flights, split=split
                )
            except SessionDrainRequired:
                # node/vocab shape change with a solve still in flight:
                # apply it, then dispatch with healing
                drain()
                new = self._dispatch(prep, allow_heal=True, split=split)
        except Exception as e:
            # deferred dispatch failed at the top tier
            # (kubernetes_tpu/resilience): no flight exists, so requeue
            # the batch for an immediate retry and flag the failure —
            # the next pop routes it through the synchronous resilient
            # cycle, where the fallback ladder owns rebuild/descent/
            # bisection. The session may have consumed a partial
            # upload: mark it stale so the next dispatch heals.
            with self.cluster.lock:
                self._session_stale.add(profile)
            self.resilience.note_async_failure(profile)
            self._solver_failed(infos, e, None, prep.step, base_cycle)
            self._requeue_immediate(infos)
            owned.pop(0)
            return
        flights.extend(new)
        # handoff point: from here the flights own this group's pods —
        # a later exception must requeue them via the flight-discard
        # path, NOT the owned-groups requeue (double-requeue hazard)
        owned.pop(0)
        # apply everything but the newest sub-flight now: each read was
        # overlapped by the dispatches above (or by the next sub-solve
        # already running on device); the survivor overlaps the next
        # pop/tensorize
        while len(flights) > 1:
            apply_one()

    def _dispatch(
        self, prep: _PreparedGroup, allow_heal: bool, split: int
    ) -> list[_InFlightSolve]:
        """Deferred dispatch normalized to a flight list (split == 1
        keeps the legacy single-flight _dispatch_group signature the
        fence tests and the sim monkeypatch)."""
        if split > 1:
            got = self._dispatch_group(
                prep, defer=True, allow_heal=allow_heal, split=split
            )
            return got if isinstance(got, list) else [got]
        return [
            self._dispatch_group(prep, defer=True, allow_heal=allow_heal)
        ]

    # -- streaming dispatcher (the device-resident solve loop) --

    def _ensure_completion_thread(self) -> None:
        """Lazily start the streaming dispatcher's completion thread:
        it parks on each dispatched solve's async D2H transfer
        (DeferredAssignments.wait) so the tunnel round trip is paid off
        the driver thread — by the time the driver's apply calls get(),
        the value is host-side and the read costs ~0. The thread holds
        no locks and touches no scheduler state beyond the in-flight
        gauge, so it cannot perturb the driver's (deterministic)
        apply order."""
        if self._completion_thread is not None:
            return
        import queue as _queue
        import threading
        import weakref

        self._completion_q = _queue.SimpleQueue()
        t = threading.Thread(
            # static target over the queue alone: a bound method would
            # pin this Scheduler (and its device session) alive for the
            # daemon thread's whole process lifetime
            target=Scheduler._completion_loop,
            args=(self._completion_q,),
            name="ktpu-stream-completion",
            daemon=True,
        )
        self._completion_thread = t
        t.start()
        # the static target keeps the Scheduler collectable; this makes
        # the thread follow it out — processes that build schedulers
        # repeatedly (restart recovery, fleet sims, bench ladders) must
        # not accumulate one parked thread + queue per instance. GC-time
        # only (atexit=False): waking a parked daemon thread during
        # interpreter shutdown exits it through C++ frames
        # (std::terminate → SIGABRT); at exit the parked threads are
        # harmless
        fin = weakref.finalize(self, self._completion_q.put, None)
        fin.atexit = False

    # the completion thread's drain loop — hot-path scoped so TPU001
    # guards it against accidental host syncs: the only device
    # interaction allowed here is the sanctioned
    # DeferredAssignments.wait (park on the async D2H; the driver's
    # get() stays the one read): ktpu: hot
    @staticmethod
    def _completion_loop(q) -> None:
        while True:
            handle = q.get()
            if handle is None:
                return  # shutdown sentinel (GC finalizer / tests)
            handle.wait()
            metrics.stream_inflight_reads.dec()

    def _stream_track(self, flights: list) -> None:
        """Hand a new slot's deferred reads to the completion thread."""
        for f in flights:
            if isinstance(f.handle, DeferredAssignments):
                metrics.stream_inflight_reads.inc()
                self._completion_q.put(f.handle)

    def run_streaming(self, max_batches: int = 10_000) -> list[BatchResult]:
        """Drain the queue through the STREAMING dispatcher: one
        persistent device-resident solve loop replacing run_pipelined's
        three modes (overlap/carry/sync) — the per-batch RTT floor
        becomes a per-event-fence floor.

        Mechanics per popped batch (mode counter ``stream``):

        - tensorize host-side (the port-occupancy staging reuses the
          previous batch's vocab scan when the cache is unchanged) and
          fold extenders/plugins/DRA as the usual pre-dispatch stage;
        - dispatch into the bounded work ring
          (SchedulerConfig.stream_depth): when the batch's occupancy
          vocabulary fingerprints identically to the previous slot's
          (ExactSolver.stream_chain_key) and no fence moved, the solve
          CHAINS on the previous batch's device-resident carry
          (BatchCarriedUsage) — occupancy advanced by earlier
          placements never round-trips through host tensorize, and
          hard shapes stop paying the drain-per-batch the carry mode
          charged;
        - assignment reads stream back asynchronously: the completion
          thread pre-waits each deferred read so the driver-side apply
          never blocks on the tunnel in steady state
          (scheduler_stream_unhidden_reads_total counts the ones that
          did — the ring drain pays at most one);
        - applies run strictly in dispatch order on the driver thread
          (determinism: the completion thread only warms transfers, it
          never reorders work).

        Fencing: each slot's prep carries its fence epoch
        (_conflict_seq/_occupancy_seq at tensorize). A conflicting
        event discards exactly the slots dispatched before it
        (scheduler_stream_slot_discard_total) — chained successors
        share the epoch and die with their parent, slots dispatched
        after the event survive. An un-chainable batch (vocabulary
        changed, columns dirtied by applies, fence moved) drains the
        ring first; hard shapes then re-tensorize against exact
        occupancy, which is always correct.

        Degraded mode: ``resilience.should_sync()`` routes the batch
        through the synchronous resilient cycle (fallback ladder,
        bisection quarantine), exactly like run_pipelined; the
        fence-discard livelock backstop is unchanged."""
        out: list[BatchResult] = []
        slots: list[_StreamSlot] = []
        depth = max(self.config.stream_depth, 1)
        self._ensure_completion_thread()
        self._streaming_active = True

        def apply_slot() -> None:
            slot = slots.pop(0)
            metrics.stream_depth.set(len(slots))
            clean = True
            for f in slot.flights:
                r = self._apply_flight(f)
                if r.progressed:
                    out.append(r)
                if r.bind_failures:
                    clean = False
            if self._last_discard_step == slot.prep.step:
                # the fence killed (at least the tail of) this slot —
                # count SLOTS, not sub-flights: one conflicting window
                # is one discard epoch
                clean = False
                metrics.stream_slot_discard_total.inc()
            if not clean:
                # a discard or assume/bind failure may have left the
                # session persist ahead of host truth (phantom
                # placement): the carry must not be chained on — drop
                # it; the next dispatch drains + heals. (Clean applies
                # need no action HERE: their column dirt only appears
                # when the next tensorize materializes the cache into
                # the snapshot, and _stream_group advances the carry
                # baseline at exactly that point.)
                solver = self.solvers.get(slot.prep.profile)
                if solver is not None:
                    solver.invalidate_stream_carry()

        def drain() -> None:
            while slots:
                apply_slot()

        batches = 0
        try:
            while batches < max_batches:
                if not slots:
                    # ring-drain boundary: the ONE point a stream-depth
                    # change (the auto-tuner's, or an operator flipping
                    # config.stream_depth on a live scheduler) may take
                    # effect — an in-flight ring keeps the depth it was
                    # dispatched under, so a shrink can never strand a
                    # dispatched-but-unapplied slot
                    depth = max(self.config.stream_depth, 1)
                if self.fleet is not None and self.fleet.maybe_resync(
                    self
                ):
                    # the partition moved: in-flight solves are fenced
                    # stale (resync bumped both fences) — drain so they
                    # discard before the next shard-scoped pop
                    drain()
                if self._waiting:
                    drain()
                    # WaitingPod settlement runs a synchronous cycle
                    metrics.pipeline_mode_total.labels("sync").inc()
                    r = self.schedule_batch()
                    batches += 1
                    if not r.progressed:
                        break
                    out.append(r)
                    continue
                t0 = self.clock.perf()
                with self.cluster.lock:
                    self._release_quarantine()
                    self._reap_expired_assumes()
                    self.queue.flush_unschedulable_leftover()
                    infos = self.queue.pop_batch(self.config.batch_size)
                    for i in infos:
                        self._in_flight[i.key] = i
                    if self._gang is not None:
                        # gang gate BEFORE base_cycle (see run_pipelined)
                        infos = self._gang_gate(infos)
                    base_cycle = self.queue.scheduling_cycle - len(infos)
                    self._refresh_pending_gauge()
                if not infos:
                    if slots:
                        drain()
                        continue  # discards/failures may requeue work
                    if self.rebalancer is not None:
                        # idle + ring drained: the safe rebalance point
                        r = BatchResult()
                        if self.rebalancer.maybe_run(self, r) > 0:
                            r.completed_at = self.clock.perf()
                            out.append(r)
                            continue
                    break
                batches += 1
                self._trace_step += 1
                if self.resilience.should_sync():
                    # degraded mode: the resilient synchronous cycle
                    # owns rebuilds, tier descent, probes, quarantine
                    metrics.pipeline_mode_total.labels("sync").inc()
                    drain()
                    r = self._run_popped(infos, t0)
                    if r.progressed:
                        out.append(r)
                    continue
                if self._discard_streak >= self._PIPELINE_FALLBACK_AFTER:
                    # livelock backstop (ADVICE r5 #2), unchanged from
                    # run_pipelined: one fence-free synchronous cycle
                    metrics.pipeline_fallback_total.inc()
                    metrics.pipeline_mode_total.labels("sync").inc()
                    self._log.warning(
                        "stream livelock backstop engaged after %d "
                        "consecutive fence discards: one synchronous "
                        "cycle", self._discard_streak,
                        extra={"step": self._trace_step},
                    )
                    drain()
                    r = self._run_popped(infos, t0)
                    self._discard_streak = 0
                    self._last_discard_step = -1
                    if r.progressed:
                        out.append(r)
                    continue
                metrics.pipeline_mode_total.labels("stream").inc()
                groups = self._group_by_profile(infos)
                owned: list[list[QueuedPodInfo]] = [g[1] for g in groups]
                try:
                    for profile, group_infos, offsets in groups:
                        self._stream_group(
                            profile, group_infos, offsets, base_cycle,
                            t0, slots, apply_slot, drain, owned, depth,
                        )
                except Exception:
                    if owned:
                        with self.cluster.lock:
                            base = self.queue.scheduling_cycle
                            for group_infos in owned:
                                for info in group_infos:
                                    self._requeue(info, base)
                    raise
            drain()
        except Exception:
            if self.flight is not None:
                path = self.flight.dump(trigger="crash")
                self._log.exception(
                    "streaming loop failed; flight recorder dump: %s",
                    path, extra={"step": self._trace_step},
                )
            raise
        finally:
            # exception escape hatch: dispatched-but-unapplied slots
            # must not strand their pods nor leave the device session
            # silently ahead of host truth
            for slot in slots:
                for f in slot.flights:
                    self._discard_flight(f)
            slots.clear()
            metrics.stream_depth.set(0)
            self._streaming_active = False
        return out

    def _stream_group(
        self,
        profile: str,
        infos: list[QueuedPodInfo],
        cycle_offsets: list[int],
        base_cycle: int,
        t0: float,
        slots: list,
        apply_slot,
        drain,
        owned: list,
        depth: int,
    ) -> None:
        """Tensorize, fold, and stream-dispatch one profile group into
        the work ring, chaining on the previous slot's device-resident
        occupancy carry whenever the fences and the occupancy
        vocabulary allow it. Falls back to drain-then-(re)tensorize —
        the always-correct path — on any mismatch."""
        solver = self.solvers[profile]
        with self.cluster.lock:
            stale = bool(self._session_stale)
            fences = (self._conflict_seq, self._occupancy_seq)
            group_pods = [i.pod for i in infos]
            plain = self._plain_batch(group_pods)
            chainable = self._stream_chainable(group_pods)
        if slots and (stale or slots[-1].prep.profile != profile):
            # a discarded solve polluted the carry, or the in-flight
            # slot belongs to another profile (its placements live only
            # in that profile's session — overlapping would double-book
            # capacity): drain before dispatching
            drain()
        may_chain = bool(
            chainable
            and slots
            and slots[-1].carried
            and slots[-1].prep.profile == profile
            and slots[-1].prep.fence == fences[0]
            and slots[-1].prep.occ_fence == fences[1]
        )
        def prepare():
            # tensorize + fold + chain-key: the one prep recipe, shared
            # by the primary path and both drain-then-retensorize
            # fallbacks (chain broke / SessionDrainRequired)
            p = self._tensorize_group(
                profile, infos, cycle_offsets, base_cycle, t0
            )
            with self.obs.span(
                "fold", trace_id=p.step, profile=profile,
                extenders=len(self.extender_clients),
                plugins=len(self.config.out_of_tree_plugins),
            ):
                self._fold_group(p)
            return p, solver.stream_chain_key(
                p.batch, p.pbatch, p.static, p.ports, p.spread,
                p.interpod,
            )

        if not plain and slots and not may_chain:
            # hard shapes need exact occupancy at tensorize unless the
            # dispatch chains on the resident carry
            drain()
        prep, chain_key = prepare()
        if (
            may_chain
            and slots
            and prep.fence == slots[-1].prep.fence
            and prep.occ_fence == slots[-1].prep.occ_fence
        ):
            # every ring apply since the last dispatch was CLEAN (an
            # unclean apply nulls the carry, failing can_chain below)
            # and no fence moved across the window, so the only column
            # dirt this tensorize's snapshot refresh materialized is
            # our own applied placements — usage the device already
            # assumed at those slots' solves. Advance the carry's
            # baseline past it, or steady-state chaining would die the
            # moment the ring first fills (every apply dirties the
            # next snapshot, and in-flight dispatches defer heals).
            with self.cluster.lock:
                solver.note_stream_applied(self.snapshot.col_versions)
        chain = bool(
            may_chain
            and slots
            and prep.nominated.empty
            and not prep.dra_active
            and prep.volume_ctx is None
            and prep.fence == slots[-1].prep.fence
            and prep.occ_fence == slots[-1].prep.occ_fence
            and solver.can_chain(chain_key, self.snapshot.col_versions)
        )
        if slots and not chain:
            if not plain:
                # the chain broke between the pre-check and the
                # tensorize (vocabulary changed, applies dirtied
                # columns, a late event): drain and RE-tensorize so the
                # occupancy tensors see every applied placement
                drain()
                prep, chain_key = prepare()
            elif prep.fence != slots[-1].prep.fence:
                # an event landed since the in-flight dispatch: node
                # TABLES may have changed, and the deferred heal is
                # only conservative for usage columns (run_pipelined's
                # stale-table hazard) — drain so this dispatch heals
                drain()
        split = self._choose_split(len(infos))
        try:
            try:
                flights = self._dispatch_stream(
                    prep, allow_heal=not slots, split=split,
                    chain=chain, chain_key=chain_key,
                )
            except SessionDrainRequired:
                # node/vocab shape change with solves still in flight:
                # apply them, then dispatch with healing (hard shapes
                # re-tensorize: their occupancy must see the applies)
                drain()
                if not plain:
                    prep, chain_key = prepare()
                flights = self._dispatch_stream(
                    prep, allow_heal=True, split=split,
                    chain=False, chain_key=chain_key,
                )
        except Exception as e:
            # deferred dispatch failed at the top tier: no flight
            # exists, so requeue for an immediate retry — the next pop
            # routes through the synchronous resilient cycle
            # (kubernetes_tpu/resilience), which owns rebuild/descent/
            # bisection
            with self.cluster.lock:
                self._session_stale.add(profile)
            self.resilience.note_async_failure(profile)
            self._solver_failed(infos, e, None, prep.step, base_cycle)
            self._requeue_immediate(infos)
            owned.pop(0)
            return
        slots.append(
            _StreamSlot(
                prep=prep, flights=flights,
                carried=bool(prep.nominated.empty),
            )
        )
        metrics.stream_depth.set(len(slots))
        self._stream_track(flights)
        # handoff point: the slot owns this group's pods now
        owned.pop(0)
        # bound the ring: apply the oldest slot(s) — their reads were
        # pre-waited by the completion thread while the newer dispatches
        # streamed down, so the drain is host work, not tunnel time
        while len(slots) > depth:
            apply_slot()

    def _dispatch_stream(
        self,
        prep: _PreparedGroup,
        allow_heal: bool,
        split: int,
        chain: bool,
        chain_key: tuple | None,
    ) -> list[_InFlightSolve]:
        """Deferred streaming dispatch normalized to a flight list (the
        stream path returns a list even unsplit — it is the one path
        that can consume/produce the cross-batch occupancy carry)."""
        got = self._dispatch_group(
            prep, defer=True, allow_heal=allow_heal, split=split,
            stream=True, chain=chain, chain_key=chain_key,
        )
        return got if isinstance(got, list) else [got]

    # -- backlog drain (the accelerator-resident mega-backlog path) --

    def drain_shape(self, chunk_pods: int, sample: int = 256):
        """The HBM budget model's inputs for draining THIS scheduler's
        queue in ``chunk_pods``-sized chunks (solver/budget.DrainShape):
        node count and padding discipline from the live cache/snapshot,
        per-family activity and row widths from a bounded sample of the
        queued pods (a 512k-pod backlog is never walked in full — the
        floor pads cover the unsampled tail conservatively, and an
        underestimate degrades to a budget miss caught by the real
        counters, never to a wrong solve)."""
        from .solver.budget import DrainShape, node_padding
        from .tensorize.plugins import PORT_PAD
        from .tensorize.schema import bucket_pow2

        with self.cluster.lock:
            n_nodes = sum(
                1
                for info in self.cache.nodes.values()
                if info.node is not None
            )
            keys = list(self.queue.entries().keys())[:sample]
        vocab_k = (
            len(self.snapshot.batch.vocab)
            if self.snapshot.batch is not None
            else 3
        )
        ports: set[int] = set()
        spread = interpod = False
        classes: set[tuple] = set()
        for key in keys:
            ns, name = key.split("/", 1)
            try:
                pod = self.cluster.get_pod(ns, name)
            except ApiError:
                continue
            ports.update(pod.host_ports())
            if pod.topology_spread_constraints:
                spread = True
            if pod.affinity is not None and (
                pod.affinity.pod_affinity is not None
                or pod.affinity.pod_anti_affinity is not None
            ):
                interpod = True
            req = pod.resource_request()
            classes.add(
                (
                    req.get("cpu", 0),
                    req.get("memory", 0),
                    tuple(sorted(pod.host_ports())),
                )
            )
        pad_mult = self.snapshot.pad_multiple
        inst = 8  # the tensorizers' INST_PAD floor
        return DrainShape(
            nodes=max(n_nodes, 1),
            chunk_pods=chunk_pods,
            vocab_k=vocab_k,
            classes=min(len(classes) or 1, 64),
            spread=spread,
            interpod=interpod,
            port_rows=max(bucket_pow2(len(ports), floor=PORT_PAD), PORT_PAD)
            if ports
            else PORT_PAD,
            spread_rows=inst,
            ipa_in_rows=inst,
            ipa_ex_rows=inst,
            # hostname topologies make every node its own domain: bound
            # the index audit by the node padding whenever a domain
            # family is active at all (conservative — d_pad is not in
            # the byte model, only the overflow clauses)
            d_pad=node_padding(max(n_nodes, 1), pad_mult)
            if (spread or interpod)
            else 8,
            mesh_devices=self._mesh_devices,
            group=max(self.solver.config.group_size, 1),
            stream_depth=max(self.config.stream_depth, 1),
            pad_multiple=pad_mult,
        )

    def _warm_start_backlog(self, report: BacklogDrainReport) -> None:
        """Mega-planner warm-start (ISSUE 19): one convex-relaxation
        solve (solver/relax.py) over the WHOLE queued backlog against
        the live snapshot, then re-key the activeQ tiebreak with the
        relaxed plan's target-node rank — pods the global plan
        co-locates pop adjacently, so each drain chunk arrives at the
        solver already packed against pre-fitted capacity. Advisory
        only: the per-chunk solves still place against cluster truth,
        so a stale plan degrades to the old ordering, never to a wrong
        binding. The relaxation's duals are exported per node group as
        the ``scheduler_relax_dual_price`` autoscaler cost signal."""
        import dataclasses

        from .api.objects import ZONE_LABELS
        from .solver.relax import RelaxConfig, RelaxSolver, group_prices

        with self.cluster.lock:
            batch = self.snapshot.update(self.cache)
            pods = self.queue.active_pods()
            slot_nodes = []
            for name in self.snapshot.names:
                info = self.cache.nodes.get(name) if name else None
                slot_nodes.append(info.node if info is not None else None)
        if not pods or batch.num_nodes == 0:
            return
        pbatch = build_pod_batch(pods, batch.vocab)
        static = build_static_tensors(
            pods, pbatch, slot_nodes, batch.padded
        )
        # the relaxation mutates its node batch's occupancy — plan on a
        # throwaway copy, cluster truth is untouched. No tail repair:
        # unranked pods just keep their FIFO order within the band.
        plan_batch = dataclasses.replace(
            batch,
            allocatable=batch.allocatable.copy(),
            used=batch.used.copy(),
            nonzero_used=batch.used[:2].copy(),
            pod_count=batch.pod_count.copy(),
        )
        solver = RelaxSolver(RelaxConfig(), repair=None)
        assigned = solver.solve(plan_batch, pbatch, static)
        stats = solver.last
        rank = {
            p.key: int(a)
            for p, a in zip(pods, assigned)
            if int(a) >= 0
        }
        with self.cluster.lock:
            report.warm_start_ranked = self.queue.reorder_active(rank)
        report.relax_iterations = stats.iterations
        report.relax_residual = stats.residual
        metrics.relax_iterations.observe(stats.iterations)
        metrics.relax_residual.set(stats.residual)
        metrics.relax_repair_rounds.observe(stats.repair_rounds)

        def zone_of(node) -> str:
            if node is not None:
                for lbl in ZONE_LABELS:
                    if lbl in node.labels:
                        return node.labels[lbl]
            return "default"

        groups = [zone_of(nd) for nd in slot_nodes]
        for grp, price in group_prices(
            stats, groups, valid=batch.valid
        ).items():
            metrics.relax_dual_price.labels(grp).set(price)
        self._log.info(
            "backlog warm-start: ranked %d/%d pods in %d relax "
            "iterations (residual %.4f)",
            report.warm_start_ranked, len(pods),
            stats.iterations, stats.residual,
            extra={"step": self._trace_step},
        )

    def drain_backlog(
        self,
        *,
        chunk_pods: int = 0,
        budget_bytes: int = 0,
        max_batches: int = 1_000_000,
        warm_start: bool | None = None,
    ) -> BacklogDrainReport:
        """Drain the queued backlog through the streaming dispatcher in
        chunk-aligned sub-batches against the resident session — the
        512k-pods x 102k-nodes path (ISSUE 12). The pod axis is cut
        into budget-planned chunks (one popped batch each) that stream
        down ``run_streaming``'s slot ring; cross-batch occupancy
        chaining keeps the port/spread/interpod carry device-resident
        across the whole drain, so hard shapes stop paying a
        drain-and-retensorize per chunk.

        Before anything dispatches, the HBM budget model
        (solver/budget.py) computes the chunk shape's per-device
        footprint from the same pad_multiple/LANE discipline the
        tensorizers use and asserts it against ``budget_bytes``
        (default: the PJRT-reported device limit). An over-budget
        chunk AUTO-SPLITS — the planner halves group-aligned,
        ``scheduler_backlog_budget_splits_total`` counts it — instead
        of OOMing mid-drain; a shape that cannot fit at any chunk size
        raises the typed ``BudgetExceeded`` with nothing dispatched.

        The estimate and the measured h2d counter delta are exported
        as the ``scheduler_backlog_hbm_*_bytes`` gauge pair so the
        model stays checkable in production."""
        from .solver import budget as hbm

        with self.cluster.lock:
            backlog = len(self.queue)
        report = BacklogDrainReport(pods=backlog)
        if backlog == 0:
            return report
        base_chunk = (
            chunk_pods
            or self.config.backlog_chunk_pods
            or self.config.batch_size
        )
        budget = hbm.device_budget_bytes(
            budget_bytes or self.config.hbm_budget_bytes
        )
        try:
            shape = self.drain_shape(base_chunk)
            est, splits = hbm.plan_chunk(shape, budget)  # BudgetExceeded -> caller
        except Exception:
            # the pre-dispatch planning path dies BEFORE run_streaming
            # (whose own crash handler would dump): a BudgetExceeded /
            # planner crash here must still leave the ring on disk —
            # the drain's flight-recorder coverage matches the loops'
            if self.flight is not None:
                path = self.flight.dump(trigger="crash")
                self._log.exception(
                    "backlog drain planning failed; flight recorder "
                    "dump: %s", path, extra={"step": self._trace_step},
                )
            raise
        chunk = est.chunk_pods
        compact = self.solver.config.compact_wire
        per_chunk = (
            est.chunk_upload_bytes_compact
            if compact
            else est.chunk_upload_bytes
        )
        n_chunks_est = max((backlog + chunk - 1) // chunk, 1)
        est_h2d = est.session_upload_bytes + (n_chunks_est - 1) * per_chunk
        metrics.backlog_budget_splits_total.inc(splits)
        metrics.backlog_hbm_estimated_bytes.set(est_h2d)
        self._log.info(
            "backlog drain: %d pods in %d-pod chunks (%d budget splits, "
            "%d B/device estimated vs %d B budget)",
            backlog, chunk, splits, est.per_device_bytes, budget,
            extra={"step": self._trace_step},
        )
        if (
            warm_start
            if warm_start is not None
            else self.config.backlog_warm_start
        ):
            self._warm_start_backlog(report)

        old_batch = self.config.batch_size
        self.config.batch_size = chunk
        self._backlog_drain_active = True
        self._drain_chunk_base = self._trace_step
        steps0 = self._trace_step
        # the drain's ROOT trace id: every chunk's spans and journal
        # records carry it (`drain_trace`), so the whole multi-chunk
        # pass reads as one trace — a chunk's own step stays its batch
        # trace id, the root ties the chunks together (the trace-id
        # stability contract tests/test_obs.py pins at a multi-chunk
        # shape)
        self._span_tags["drain_trace"] = steps0
        if self.journal is not None:
            self.journal.tags["drain_trace"] = steps0
        h2d0 = metrics.h2d_bytes_total._value.get()
        chained0 = sum(
            s.dispatch_counts.get("stream_chained", 0)
            for s in self.solvers.values()
        )
        if self.tuner is not None:
            # arm the drain-chunk controller: candidates re-run the
            # budget model (estimate + index-headroom audit) as their
            # guardrail, so a tuner-proposed chunk can never raise
            # BudgetExceeded from the dispatch path. The tuner adjusts
            # config.batch_size between pops — chunk boundaries — and
            # the streaming ring never sees a mid-chunk change.
            self.tuner.on_drain_start(self, chunk, budget)
        t0 = self.clock.perf()
        try:
            with self.obs.span(
                "drain_backlog", trace_id=steps0, pods=backlog,
                chunk_pods=chunk, budget_splits=splits,
                **self._span_tags,
            ):
                results = self.run_streaming(max_batches=max_batches)
        finally:
            self.config.batch_size = old_batch
            self._backlog_drain_active = False
            self._span_tags.pop("drain_trace", None)
            if self.tuner is not None:
                self.tuner.on_drain_end(self)
                report.final_chunk_pods = (
                    self.tuner.knob_values().get("backlog_chunk", chunk)
                )
            if self.journal is not None:
                self.journal.tags.pop("drain_chunk", None)
                self.journal.tags.pop("drain_trace", None)
        dt = self.clock.perf() - t0

        report.results = results
        report.drained = sum(len(r.scheduled) for r in results)
        report.unschedulable = sum(len(r.unschedulable) for r in results)
        report.chunks = self._trace_step - steps0
        report.chunk_pods = chunk
        report.budget_splits = splits
        report.budget_bytes = budget
        report.drain_seconds = dt
        report.pods_per_sec = report.drained / dt if dt > 0 else 0.0
        lats = sorted(x for r in results for x in r.e2e_latencies)
        if lats:
            report.p99_e2e_latency_s = lats[int(0.99 * (len(lats) - 1))]
        solves = sorted(
            r.solve_seconds for r in results if r.solve_seconds > 0
        )
        if solves:
            report.median_chunk_solve_s = solves[len(solves) // 2]
        report.stream_chained_batches = (
            sum(
                s.dispatch_counts.get("stream_chained", 0)
                for s in self.solvers.values()
            )
            - chained0
        )
        report.chain_fraction = report.stream_chained_batches / max(
            report.chunks - 1, 1
        )
        report.estimated_per_device_bytes = est.per_device_bytes
        report.estimated_h2d_bytes = est_h2d
        report.measured_h2d_bytes = int(
            metrics.h2d_bytes_total._value.get() - h2d0
        )
        metrics.backlog_chunks_total.inc(report.chunks)
        metrics.backlog_drain_seconds.observe(dt)
        metrics.backlog_hbm_measured_bytes.set(report.measured_h2d_bytes)
        return report

    def relax_plan_backlog(self, pods=None) -> "dict[str, str | None]":
        """The fleet drain COORDINATOR's planning half (ROADMAP #5a):
        one relax mega-solve over the backlog, returned as a pod-key ->
        planned-node-name map (None = the relaxation left the pod
        unplaced). Same solve the warm-start runs (ISSUE 19), but here
        the OUTPUT is the plan itself — ``fleet/drain.py`` partitions
        the backlog by the shard that owns each planned node, so every
        replica drains pods the global plan already packed against its
        own nodes. Advisory like the warm-start: a stale plan only
        mis-shards (extra cross-shard CAS traffic), never mis-binds."""
        import dataclasses

        from .solver.relax import RelaxConfig, RelaxSolver

        with self.cluster.lock:
            batch = self.snapshot.update(self.cache)
            if pods is None:
                pods = self.queue.active_pods()
            slot_nodes = []
            for name in self.snapshot.names:
                info = self.cache.nodes.get(name) if name else None
                slot_nodes.append(info.node if info is not None else None)
        if not pods or batch.num_nodes == 0:
            return {p.key: None for p in pods}
        pbatch = build_pod_batch(pods, batch.vocab)
        static = build_static_tensors(
            pods, pbatch, slot_nodes, batch.padded
        )
        plan_batch = dataclasses.replace(
            batch,
            allocatable=batch.allocatable.copy(),
            used=batch.used.copy(),
            nonzero_used=batch.used[:2].copy(),
            pod_count=batch.pod_count.copy(),
        )
        assigned = RelaxSolver(RelaxConfig(), repair=None).solve(
            plan_batch, pbatch, static
        )
        plan: dict = {}
        for p, a in zip(pods, assigned):
            a = int(a)
            plan[p.key] = (
                batch.names[a] if 0 <= a < batch.num_nodes else None
            )
        return plan

    def fleet_drain_backlog(
        self,
        *,
        chunk_pods: int = 0,
        budget_bytes: int = 0,
        max_batches: int = 1_000_000,
        warm_start: bool | None = False,
        plan_keys=None,
    ) -> dict:
        """Replica half of the FLEET backlog drain (ROADMAP #5a):
        claim drain leases from the hub ledger and drain each through
        this replica's own ``drain_backlog`` slot ring until nothing is
        claimable. The claim adopts the lease's pods into this queue
        and — given ``plan_keys``, the full plan's key set — sheds pods
        the plan leased elsewhere (ring routing filled the queue by
        pod-key hash; the drain re-partitions by planned-node owner).
        Each pass runs under this replica's slice of the fleet HBM
        budget (``split_fleet_budget``); a lease completes at the hub
        only once none of its pods is still live in the queue, so a
        partially-drained lease stays reassignable. Warm-start defaults
        OFF — the global plan already packed each partition; pass
        ``warm_start=True`` to re-rank locally anyway."""
        from .solver import budget as hbm

        if self.fleet is None:
            raise RuntimeError("fleet_drain_backlog requires fleet mode")
        total = hbm.device_budget_bytes(
            budget_bytes or self.config.hbm_budget_bytes
        )
        my_budget = hbm.split_fleet_budget(
            total,
            len(self.fleet.membership.universe),
            replica_index=self.fleet.shard,
        )
        t0 = self.clock.perf()
        leases: list = []
        results: list = []
        reports: list = []
        drained = 0
        while True:
            lease = self.fleet.drain_claim(self, plan_keys)
            if not lease:
                break
            lease_keys = [str(k) for k in lease.get("keys") or []]
            rep = self.drain_backlog(
                chunk_pods=chunk_pods,
                budget_bytes=my_budget,
                max_batches=max_batches,
                warm_start=warm_start,
            )
            drained += rep.drained
            results.extend(rep.results)
            reports.append(rep)
            # complete only when no lease pod is still live in the
            # queue: unschedulable stragglers stay THIS replica's pods
            # through the routing the claim adopted them under, and an
            # un-completed lease re-serves (or returns on death) so the
            # ledger never strands them
            with self.cluster.lock:
                live = set(self.queue.entries())
            remaining = sum(1 for k in lease_keys if k in live)
            completed = False
            if remaining == 0:
                completed = self.fleet.drain_complete(lease["id"])
            leases.append(
                {
                    "id": lease["id"],
                    "kind": lease.get("kind", ""),
                    "pods": len(lease_keys),
                    "completed": completed,
                    "remaining": remaining,
                }
            )
            if remaining:
                break  # stragglers need outside help; don't spin
        dt = self.clock.perf() - t0
        metrics.fleet_drain_replica_seconds.observe(dt)
        return {
            "replica": self.fleet.replica,
            "leases": leases,
            "drained": drained,
            "seconds": dt,
            "pods_per_sec": drained / dt if dt > 0 else 0.0,
            "budget_bytes": my_budget,
            "results": results,
            "reports": reports,
        }

    def hub_status(self) -> "dict | None":
        """The ``GET /debug/hub`` body: the occupancy hub's role /
        epoch / replication cursors plus this replica's client-side
        failover view (fleet/runtime.py). None when this scheduler is
        not a fleet replica; raises ExchangeUnreachable while no hub
        endpoint answers (the HTTP handler maps it to 503)."""
        if self.fleet is None:
            return None
        return self.fleet.hub_status()

    @property
    def pending(self) -> int:
        """Work the loop must still drive: queued pods, pods parked at
        Permit, AND quarantined pods — without the latter two, a serve
        drain loop gated on pending would stop ticking while WaitingPods
        still need their timeout settled or a quarantine TTL still needs
        its re-admit, both of which happen at the next cycle's pop."""
        if self.slo is not None:
            # idle heartbeat for the SLO engine: the serve drain loop
            # polls pending every iteration, so a degraded health flip
            # heals by time even when no batch ever applies again
            self.slo.tick()
        with self.cluster.lock:
            return (
                len(self.queue)
                + len(self._waiting)
                + len(self._quarantine)
            )
