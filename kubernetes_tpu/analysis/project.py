"""Project-wide symbol table and cross-module call graph.

PR 1's :mod:`callgraph` is deliberately intra-module: a bare-name call
resolves inside one file and ``nr.rtc_score(...)`` is not followed.
That was the right precision/recall trade for TPU001's per-file scope,
but it is structurally blind to the bug classes the fleet tier grew in
PRs 11–15 — lock-order inversions that span ``state/cluster.py`` and
``fleet/occupancy.py``, fence checks hidden behind a helper in another
file, and a ``# ktpu: hot`` function calling a cross-module helper that
blocks on the device.

:class:`ProjectGraph` closes the gap. It is still name-based and
best-effort (stdlib-only, no type checker), but it resolves:

- ``import a.b as m`` / ``from .mod import sym`` bindings, anywhere in
  the file (this codebase imports inside ``__init__`` bodies on
  purpose) — including relative imports, resolved against the module's
  package path;
- constructor calls ``C(...)`` to ``C.__init__`` across modules;
- attribute types: ``self.x = ClusterState(...)``, ``self.x = param``
  with an annotated param, annotated params themselves, and
  module-level singletons (``WATCHER = CompileWatcher()``), so
  ``self.cluster.lock`` and ``self.exchange.stage(...)`` resolve to the
  owning class — when an attribute is assigned conditionally with two
  types (``RemoteOccupancyExchange`` | ``OccupancyExchange``) BOTH are
  kept and analyses union over the candidates;
- method lookup through project-local base classes.

Unresolvable receivers stay unresolved — passes treat "unknown" as
"no edge", never as an error, so precision is preserved: a LOCK002
edge or a FENCE001 "fence reached" verdict only ever comes from a
positive resolution.

Node identity is ``(module.rel, qualname)``; helpers below expose the
global scope BFS (TPU004), reverse reachability (FENCE001), and the
transitive "may acquire" closure (LOCK002).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .callgraph import ModuleGraph, own_nodes, scoped_graph
from .core import AnalysisContext, SourceModule

# lock constructors recognized for LOCK002 lock-identity registration
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


@dataclass
class LockDecl:
    """One lock attribute: ``self.<attr> = threading.Lock()`` in a class
    body (any method, in practice ``__init__``)."""

    lock_id: str  # "<rel>::<Class>.<attr>"
    cls: str
    attr: str
    kind: str  # "Lock" | "RLock" | "Condition"
    rel: str
    line: int

    @property
    def reentrant(self) -> bool:
        return self.kind == "RLock"


@dataclass
class ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    methods: set = field(default_factory=set)
    bases: list = field(default_factory=list)  # resolved (rel, name) pairs
    # attr -> set of candidate (rel, class) types
    attr_types: dict = field(default_factory=dict)
    # attr -> LockDecl
    locks: dict = field(default_factory=dict)
    # attr -> line of the `# ktpu: replicated` registration
    replicated: dict = field(default_factory=dict)


def module_name(rel: str) -> str:
    """Dotted module name for a package-relative path; bare fixture
    filenames ("a.py") become plain names ("a")."""
    name = rel[:-3] if rel.endswith(".py") else rel
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


class ProjectGraph:
    """All modules of one analysis run, cross-linked."""

    def __init__(self, modules, ctx: AnalysisContext):
        self.ctx = ctx
        self.modules: dict[str, SourceModule] = {}
        self.graphs: dict[str, ModuleGraph] = {}
        self._intra_scopes: dict[str, tuple[set, set]] = {}
        for m in modules:
            if m.rel in self.modules:  # duplicate path on the CLI
                continue
            self.modules[m.rel] = m
            graph, traced, hot = scoped_graph(m, ctx)
            self.graphs[m.rel] = graph
            self._intra_scopes[m.rel] = (traced, hot)
        self._by_name = {module_name(rel): rel for rel in self.modules}
        # (rel, class name) -> ClassInfo ; class name -> [ClassInfo]
        self.classes: dict[tuple, ClassInfo] = {}
        self._imports: dict[str, dict] = {}  # rel -> local name -> binding
        self._module_vars: dict[str, dict] = {}  # rel -> var -> type set
        self.edges: dict[tuple, set] = {}  # (rel, qual) -> {(rel, qual)}
        self._collect_classes()
        self._collect_imports()
        self._collect_module_vars()
        self._infer_attr_types()
        self._resolve_bases()
        self._build_edges()

    # -- symbol collection -------------------------------------------------

    def _collect_classes(self) -> None:
        for rel, m in self.modules.items():
            for stmt in m.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    info = ClassInfo(name=stmt.name, rel=rel, node=stmt)
                    graph = self.graphs[rel]
                    info.methods = set(
                        graph._class_methods.get(stmt.name, set())
                    )
                    self.classes[(rel, stmt.name)] = info
        self._classes_by_name: dict[str, list] = {}
        for (rel, name), info in self.classes.items():
            self._classes_by_name.setdefault(name, []).append(info)

    def _resolve_module(self, dotted: str, from_rel: str, level: int) -> str | None:
        """Dotted module name (possibly relative) -> rel path of a module
        in this project, or None."""
        if level:
            base = module_name(from_rel).split(".")
            if not from_rel.endswith("/__init__.py"):
                base = base[:-1]  # strip the module leaf -> its package
            up = level - 1  # level 1 = current package
            base = base[: len(base) - up] if up <= len(base) else []
            dotted = ".".join(base + ([dotted] if dotted else []))
        cand = self._by_name.get(dotted)
        return cand

    def _collect_imports(self) -> None:
        for rel, m in self.modules.items():
            table: dict[str, tuple] = {}
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        target = self._resolve_module(alias.name, rel, 0)
                        if target:
                            local = alias.asname or alias.name.split(".")[0]
                            # "import a.b" binds "a"; only alias form gives
                            # a direct handle on the leaf module
                            if alias.asname or "." not in alias.name:
                                table[local] = ("module", target, None)
                elif isinstance(node, ast.ImportFrom):
                    target = self._resolve_module(
                        node.module or "", rel, node.level
                    )
                    for alias in node.names:
                        local = alias.asname or alias.name
                        if target is None:
                            continue
                        sub = self._resolve_module(
                            (node.module or "") + "." + alias.name
                            if node.module
                            else alias.name,
                            rel,
                            node.level,
                        )
                        if sub is not None:
                            # "from . import occupancy" — a module binding
                            table[local] = ("module", sub, None)
                        else:
                            table[local] = ("symbol", target, alias.name)
            self._imports[rel] = table

    def _collect_module_vars(self) -> None:
        """Module-level singleton types: ``WATCHER = CompileWatcher()``."""
        for rel, m in self.modules.items():
            env: dict[str, frozenset] = {}
            for stmt in m.tree.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ):
                    types = self._type_of_ctor(stmt.value.func, rel)
                    if types:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                env[t.id] = types
            self._module_vars[rel] = env

    # -- type resolution ---------------------------------------------------

    def resolve_symbol(self, name: str, rel: str):
        """A bare name in module `rel` -> ("class", ClassInfo) |
        ("function", (rel, qual)) | ("module", rel) | None."""
        if (rel, name) in self.classes:
            return ("class", self.classes[(rel, name)])
        graph = self.graphs.get(rel)
        if graph is not None and name in graph._module_level:
            return ("function", (rel, name))
        binding = self._imports.get(rel, {}).get(name)
        if binding is None:
            return None
        kind, target, sym = binding
        if kind == "module":
            return ("module", target)
        if (target, sym) in self.classes:
            return ("class", self.classes[(target, sym)])
        tgraph = self.graphs.get(target)
        if tgraph is not None and sym in tgraph._module_level:
            return ("function", (target, sym))
        types = self._module_vars.get(target, {}).get(sym)
        if types:
            # imported singleton: treat the name as a value of that type
            return ("value", types)
        return None

    def _type_of_ctor(self, func: ast.expr, rel: str) -> frozenset:
        """Types produced by calling `func` as a constructor."""
        if isinstance(func, ast.Name):
            got = self.resolve_symbol(func.id, rel)
            if got and got[0] == "class":
                return frozenset({(got[1].rel, got[1].name)})
        elif isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            got = self.resolve_symbol(func.value.id, rel)
            if got and got[0] == "module":
                target = got[1]
                if (target, func.attr) in self.classes:
                    return frozenset({(target, func.attr)})
        return frozenset()

    def _type_of_annotation(self, ann: ast.expr, rel: str) -> frozenset:
        """Best-effort class types named by an annotation; unwraps
        ``X | None`` and ``Optional[X]``."""
        if ann is None:
            return frozenset()
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return self._type_of_annotation(
                ann.left, rel
            ) | self._type_of_annotation(ann.right, rel)
        if isinstance(ann, ast.Subscript):
            return self._type_of_annotation(ann.slice, rel)
        if isinstance(ann, ast.Constant):
            if isinstance(ann.value, str):
                try:
                    return self._type_of_annotation(
                        ast.parse(ann.value, mode="eval").body, rel
                    )
                except SyntaxError:
                    return frozenset()
            return frozenset()
        return self._type_of_ctor(ann, rel)

    def _param_types(self, fnode, rel: str) -> dict:
        env: dict[str, frozenset] = {}
        a = fnode.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        ):
            types = self._type_of_annotation(arg.annotation, rel)
            if types:
                env[arg.arg] = types
        return env

    def _infer_attr_types(self) -> None:
        """``self.x = <ctor>`` / ``self.x = <annotated param>`` inside any
        method registers candidate types (and lock declarations) for the
        enclosing class; ``# ktpu: replicated`` trailing the assignment
        registers replicated state (FENCE001)."""
        for (rel, cname), cinfo in self.classes.items():
            m = self.modules[rel]
            graph = self.graphs[rel]
            for qual, finfo in graph.functions.items():
                if finfo.cls != cname or finfo.parent:
                    continue
                env = self._param_types(finfo.node, rel)
                for node in own_nodes(finfo.node):
                    # `self.x = ...` and `self.x: T = ...` both register
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AnnAssign) and node.value:
                        targets = [node.target]
                    else:
                        continue
                    for t in targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        if isinstance(node.value, ast.Call):
                            lk = _lock_kind(node.value.func)
                            if lk:
                                cinfo.locks[t.attr] = LockDecl(
                                    lock_id=f"{rel}::{cname}.{t.attr}",
                                    cls=cname,
                                    attr=t.attr,
                                    kind=lk,
                                    rel=rel,
                                    line=node.lineno,
                                )
                                continue
                            types = self._type_of_ctor(node.value.func, rel)
                        elif isinstance(node.value, ast.Name):
                            types = env.get(node.value.id, frozenset())
                        else:
                            types = frozenset()
                        if isinstance(node, ast.AnnAssign):
                            types = types | self._type_of_annotation(
                                node.annotation, rel
                            )
                        if types:
                            cinfo.attr_types[t.attr] = (
                                cinfo.attr_types.get(t.attr, frozenset())
                                | types
                            )
                        if m.replicated_mark(node):
                            cinfo.replicated[t.attr] = node.lineno
                # annotated attribute declarations in the class body also
                # count (dataclass-style)
            for stmt in cinfo.node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    types = self._type_of_annotation(stmt.annotation, rel)
                    if types:
                        cinfo.attr_types[stmt.target.id] = (
                            cinfo.attr_types.get(stmt.target.id, frozenset())
                            | types
                        )

    def _resolve_bases(self) -> None:
        for (rel, _), cinfo in self.classes.items():
            for b in cinfo.node.bases:
                types = self._type_of_ctor(b, rel)
                cinfo.bases.extend(sorted(types))

    def lookup_method(self, ctype: tuple, name: str) -> tuple | None:
        """(rel, "Cls.meth") for a method on class `ctype` or a
        project-local base."""
        seen = set()
        work = [ctype]
        while work:
            cur = work.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            cinfo = self.classes.get(cur)
            if cinfo is None:
                continue
            if name in cinfo.methods:
                return (cinfo.rel, f"{cinfo.name}.{name}")
            work.extend(cinfo.bases)
        return None

    # -- value typing inside one function ----------------------------------

    def local_env(self, rel: str, finfo) -> dict:
        """name -> candidate types for params and simple locals."""
        env = dict(self._param_types(finfo.node, rel))
        cinfo = self.classes.get((rel, finfo.cls)) if finfo.cls else None
        if cinfo is not None:
            env.setdefault("self", frozenset({(cinfo.rel, cinfo.name)}))
        for node in own_nodes(finfo.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                types = self.expr_types(node.value, rel, env, cinfo)
                if types:
                    env[t.id] = env.get(t.id, frozenset()) | types
        return env

    def expr_types(self, expr, rel: str, env: dict, cinfo) -> frozenset:
        """Candidate class types of a value expression (best effort)."""
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            got = self.resolve_symbol(expr.id, rel)
            if got and got[0] == "value":
                return got[1]
            return self._module_vars.get(rel, {}).get(expr.id, frozenset())
        if isinstance(expr, ast.Call):
            return self._type_of_ctor(expr.func, rel)
        if isinstance(expr, ast.Attribute):
            base = self.expr_types(expr.value, rel, env, cinfo)
            if (
                not base
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and cinfo is not None
            ):
                base = frozenset({(cinfo.rel, cinfo.name)})
            out = frozenset()
            for bt in base:
                binfo = self.classes.get(bt)
                if binfo:
                    out |= binfo.attr_types.get(expr.attr, frozenset())
            return out
        return frozenset()

    # -- edges -------------------------------------------------------------

    def _build_edges(self) -> None:
        for rel, graph in self.graphs.items():
            for qual, finfo in graph.functions.items():
                node_id = (rel, qual)
                out = self.edges.setdefault(node_id, set())
                # nested defs inherit the parent's scope
                for oq, oinfo in graph.functions.items():
                    if oinfo.parent == qual:
                        out.add((rel, oq))
                env = None  # built lazily: most functions are call-light
                for node in own_nodes(finfo.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if env is None:
                        env = self.local_env(rel, finfo)
                    out |= self.call_targets(rel, finfo, node, env)

    def call_targets(self, rel: str, finfo, call: ast.Call, env=None) -> set:
        """Node ids one ast.Call may dispatch to. Supersedes the
        intra-module resolution in :class:`ModuleGraph` (same bare-name
        and ``self.method`` rules) and adds the cross-module cases."""
        out: set = set()
        f = call.func
        graph = self.graphs[rel]
        cinfo = self.classes.get((rel, finfo.cls)) if finfo.cls else None
        if isinstance(f, ast.Name):
            # nested function in an enclosing FUNCTION scope wins, then
            # module level / imports — never a sibling method (needs
            # `self.`), mirroring ModuleGraph._resolve_calls
            scope = finfo.qualname
            while scope and scope != finfo.cls:
                cand = f"{scope}.{f.id}"
                if cand in graph.functions:
                    out.add((rel, cand))
                    return out
                scope = scope.rpartition(".")[0]
            got = self.resolve_symbol(f.id, rel)
            if got is None:
                return out
            if got[0] == "function":
                out.add(got[1])
            elif got[0] == "class":
                init = self.lookup_method((got[1].rel, got[1].name), "__init__")
                if init:
                    out.add(init)
            return out
        if not isinstance(f, ast.Attribute):
            return out
        if isinstance(f.value, ast.Name):
            if f.value.id == "self" and finfo.cls:
                hit = self.lookup_method((rel, finfo.cls), f.attr)
                if hit:
                    out.add(hit)
                return out
            got = self.resolve_symbol(f.value.id, rel)
            if got and got[0] == "module":
                target = got[1]
                tgraph = self.graphs.get(target)
                if tgraph and f.attr in tgraph._module_level:
                    out.add((target, f.attr))
                elif (target, f.attr) in self.classes:
                    init = self.lookup_method((target, f.attr), "__init__")
                    if init:
                        out.add(init)
                return out
        # value.method(...): type the receiver
        if env is None:
            env = self.local_env(rel, finfo)
        types = self.expr_types(f.value, rel, env, cinfo)
        for t in sorted(types):
            hit = self.lookup_method(t, f.attr)
            if hit:
                out.add(hit)
        return out

    # -- reachability helpers ----------------------------------------------

    def function(self, node_id: tuple):
        graph = self.graphs.get(node_id[0])
        return graph.functions.get(node_id[1]) if graph else None

    def all_nodes(self):
        for rel, graph in self.graphs.items():
            for qual in graph.functions:
                yield (rel, qual)

    def _barrier(self, node_id: tuple) -> bool:
        rel, qual = node_id
        m = self.modules.get(rel)
        info = self.function(node_id)
        if m is None or info is None:
            return False
        if m.is_cold(info.node):
            return True
        return self.ctx.is_sanctioned(m.rel, qual)

    def global_scopes(self) -> tuple[set, set, dict]:
        """(traced, hot, via) over the PROJECT graph. `via[node]` is the
        predecessor on one shortest root path — for explainable findings
        ("reached from hot root X via Y")."""
        jit_roots, hot_roots = set(), set()
        for rel, graph in self.graphs.items():
            jit_roots |= {(rel, q) for q in graph._jit_roots}
            hot_roots |= {(rel, q) for q in graph._hot_roots}
        via: dict = {}
        traced = self._bfs(jit_roots, via)
        hot = self._bfs(hot_roots, via)
        return traced, hot, via

    def _bfs(self, roots: set, via: dict) -> set:
        seen: set = set()
        work = sorted(r for r in roots if not self._barrier(r))
        while work:
            cur = work.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in sorted(self.edges.get(cur, ())):
                if nxt not in seen and not self._barrier(nxt):
                    via.setdefault(nxt, cur)
                    work.append(nxt)
        return seen

    def intra_scopes(self, rel: str) -> tuple[set, set]:
        return self._intra_scopes.get(rel, (set(), set()))

    def reaches(self, targets: set) -> set:
        """All nodes from which some node in `targets` is reachable
        (including the targets themselves) — reverse closure."""
        rev: dict[tuple, set] = {}
        for src, outs in self.edges.items():
            for dst in outs:
                rev.setdefault(dst, set()).add(src)
        seen = set()
        work = sorted(targets)
        while work:
            cur = work.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(sorted(rev.get(cur, set()) - seen))
        return seen

    def root_chain(self, node_id: tuple, via: dict, limit: int = 6) -> list:
        """Root-to-node qualname chain for messages."""
        chain = [node_id]
        while node_id in via and len(chain) < limit:
            node_id = via[node_id]
            chain.append(node_id)
        chain.reverse()
        return chain


def _lock_kind(func: ast.expr) -> str | None:
    """threading.Lock / threading.RLock / threading.Condition ctor?"""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        if func.value.id == "threading" and func.attr in _LOCK_FACTORIES:
            return func.attr
    if isinstance(func, ast.Name) and func.id in _LOCK_FACTORIES:
        return func.id
    return None


class ProjectPass:
    """Base for passes that need the whole project: one run per
    analysis invocation, findings anchored to individual modules."""

    rule = "KTPU998"
    title = ""

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        raise NotImplementedError


def build_project(modules, ctx: AnalysisContext) -> ProjectGraph:
    return ProjectGraph(modules, ctx)
