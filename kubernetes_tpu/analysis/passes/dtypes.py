"""TPU003 — dtype discipline in solver/ops tensor constructors.

``jnp.array([True])`` / ``jnp.zeros(n)`` / ``jnp.full(n, 0.5)`` without
an explicit dtype take jax's weak-type defaults: the array's dtype then
depends on x64 mode and on the literal's Python type, which silently
forks the jit cache (same shapes, different dtypes -> recompile) and
upcasts int64 node tables through float64 intermediates. Under ``ops/``
and ``solver/`` every constructor names its dtype; a float literal
without one is called out specifically (the classic weak-float leak).

Positional dtypes count (``jnp.zeros(n, jnp.int32)``), as does
``dtype=``; ``jnp.zeros_like``/``astype`` are inherently typed and out
of scope of the constructor check.

A second clause polices NARROW FLATTENED INDICES (the 512k x 102k
scale audit, ISSUE 12): ``(a * n + b).astype(jnp.int32)`` — a product
of index-like values narrowed to a sub-64-bit integer in the same
expression. At pod·node scale (5.2e10) such a product wraps int32
silently on device; the flattening must happen in int64 (or the
operands must be provably clamped first, in which case the narrowing
belongs on a separate named value with the bound in a comment, which
also moves it out of this purely syntactic check's reach).
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass

# constructor -> index of the positional dtype slot
_CONSTRUCTORS = {"array": 1, "zeros": 1, "ones": 1, "full": 2}


def _has_float_literal(expr: ast.expr) -> bool:
    return any(
        isinstance(n, ast.Constant) and isinstance(n.value, float)
        for n in ast.walk(expr)
    )


_NARROW_INT_DTYPES = {"int32", "int16", "int8"}


def _is_narrow_int_dtype(expr: ast.expr) -> bool:
    """jnp.int32 / np.int32 (and narrower) attribute references."""
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id in ("jnp", "np", "numpy")
        and expr.attr in _NARROW_INT_DTYPES
    )


def _has_mult(expr: ast.expr) -> bool:
    return any(
        isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult)
        for n in ast.walk(expr)
    )


def _looks_float(expr: ast.expr) -> bool:
    """Float-arithmetic receivers (score normalization narrowed to its
    documented 0..100 range) are not index flattening: a float literal
    or a true division anywhere in the expression marks them."""
    if _has_float_literal(expr):
        return True
    return any(
        isinstance(n, ast.BinOp) and isinstance(n.op, ast.Div)
        for n in ast.walk(expr)
    )


class DtypeDisciplinePass(Pass):
    rule = "TPU003"
    title = "missing explicit dtype"

    def run(self, module, ctx):
        if not any(module.rel.startswith(p) for p in ctx.dtype_paths):
            return []
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # narrow flattened index: (a * n + b).astype(jnp.int32) —
            # the product may exceed 2^31 at pod·node scale and the
            # narrowing masks the wrap (widen to int64 before
            # flattening, or clamp into a named value first)
            astype_dtype = None
            if isinstance(f, ast.Attribute) and f.attr == "astype":
                if len(node.args) == 1 and not node.keywords:
                    astype_dtype = node.args[0]
                elif not node.args:
                    astype_dtype = next(
                        (
                            kw.value
                            for kw in node.keywords
                            if kw.arg == "dtype"
                        ),
                        None,
                    )
            if (
                astype_dtype is not None
                and _is_narrow_int_dtype(astype_dtype)
                and _has_mult(f.value)
                and not _looks_float(f.value)
            ):
                findings.append(
                    Finding(
                        self.rule, module.path, node.lineno,
                        "flattened-index product narrowed to a "
                        "sub-64-bit integer in one expression (the "
                        "product can wrap before the cast)",
                        hint="flatten in int64 (astype(jnp.int64) on "
                        "the operands) or clamp into a named value "
                        "whose bound a comment states, then narrow",
                    )
                )
                continue
            if not (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "jnp"
                and f.attr in _CONSTRUCTORS
            ):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if len(node.args) > _CONSTRUCTORS[f.attr]:
                continue  # positional dtype
            detail = (
                "a bare float literal rides the weak-type default"
                if any(_has_float_literal(a) for a in node.args)
                else "dtype falls to the weak-type default"
            )
            findings.append(
                Finding(
                    self.rule, module.path, node.lineno,
                    f"jnp.{f.attr}(...) without explicit dtype ({detail})",
                    hint="pass dtype= (e.g. jnp.int64/jnp.bool_) so the "
                    "jit cache keys stay stable across x64 modes",
                )
            )
        return findings
