"""Reconstruct one pod's scheduling history from a decision journal (or
a flight-recorder dump): the `kubectl describe pod` events story, but
sourced from the scheduler's own trace layer and including per-plugin
rejection attribution.

Input is any JSONL stream mixing ``{"k": "dec"}`` decision records and
``{"k": "span"}`` spans (a journal file, a flight-recorder dump, or the
``/debug/flightrecorder`` JSON body re-flattened by the CLI). Pods
match by exact uid, exact ``ns/name`` key, or bare pod name.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .journal import TERMINAL_OUTCOMES, summarize_plugins


@dataclass
class Explanation:
    ref: str
    records: list[dict] = field(default_factory=list)  # journal order
    spans: list[dict] = field(default_factory=list)  # terminal batch's spans

    @property
    def found(self) -> bool:
        return bool(self.records)

    @property
    def terminal(self) -> dict | None:
        """The pod's last terminal-outcome record (None = still open:
        every record is a permit_wait/discarded intermediate)."""
        for rec in reversed(self.records):
            if rec.get("outcome") in TERMINAL_OUTCOMES:
                return rec
        return None

    def render(self) -> str:
        if not self.records:
            return f"pod {self.ref!r}: no journal records found"
        first = self.records[0]
        uid = first.get("uid") or "?"
        lines = [f"pod {first['pod']} (uid {uid}): {len(self.records)} record(s)"]
        term = self.terminal
        if term is None:
            last = self.records[-1]
            lines.append(
                f"  state: OPEN — last record is {last['outcome']!r} at "
                f"step {last['step']} (no terminal outcome yet)"
            )
        elif term["outcome"] == "bound":
            lines.append(
                f"  terminal outcome: bound to {term.get('node', '?')} "
                f"(step {term['step']}, t={term['t']})"
            )
        else:
            lines.append(
                f"  terminal outcome: {term['outcome']} "
                f"(step {term['step']}, t={term['t']})"
            )
            if term.get("plugins"):
                lines.append(f"    plugins: {summarize_plugins(term['plugins'])}")
            if term.get("reason"):
                lines.append(f"    reason: {term['reason']}")
        lines.append("  history:")
        for rec in self.records:
            bits = [
                f"step {rec['step']}",
                f"cycle {rec['cycle']}",
                f"t={rec['t']}",
                rec["outcome"],
            ]
            if rec.get("node"):
                bits.append(f"-> {rec['node']}")
            if rec.get("nominated"):
                bits.append(f"nominated={rec['nominated']}")
            if rec.get("attempts"):
                bits.append(f"attempt {rec['attempts']}")
            if rec.get("drain_chunk") is not None:
                # backlog drains (Scheduler.drain_backlog) tag records
                # with the chunk that solved them
                bits.append(f"drain_chunk={rec['drain_chunk']}")
            line = "    " + " ".join(bits)
            if rec.get("plugins"):
                line += f"  [{summarize_plugins(rec['plugins'])}]"
            if rec.get("reason"):
                line += f"  ({rec['reason']})"
            lines.append(line)
        if self.spans:
            lines.append("  spans of the terminal batch:")
            for sp in self.spans:
                indent = "      " if sp.get("parent") else "    "
                lines.append(
                    f"{indent}{sp['name']}: {sp['dur'] * 1e3:.3f} ms"
                    + (f" {sp['attrs']}" if sp.get("attrs") else "")
                )
        return "\n".join(lines)


def parse_stream(lines) -> tuple[list[dict], list[dict]]:
    """(decisions, spans) from a JSONL iterable; unknown/broken lines
    are skipped (a flight-recorder dump may be truncated mid-crash)."""
    decisions: list[dict] = []
    spans: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        kind = rec.get("k") if isinstance(rec, dict) else None
        if kind == "dec":
            decisions.append(rec)
        elif kind == "span":
            spans.append(rec)
    return decisions, spans


def _matches(rec: dict, ref: str) -> bool:
    if rec.get("uid") == ref or rec.get("pod") == ref:
        return True
    pod = rec.get("pod") or ""
    return "/" in pod and pod.split("/", 1)[1] == ref


def explain_pod(
    decisions: list[dict], ref: str, spans: list[dict] | None = None
) -> Explanation:
    records = [r for r in decisions if _matches(r, ref)]
    out = Explanation(ref=ref, records=records)
    term = out.terminal
    if term is not None and spans:
        out.spans = [s for s in spans if s.get("trace") == term["step"]]
    return out
