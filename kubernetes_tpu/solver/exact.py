"""Exact-parity solver: a lax.scan over pods in queue order (SURVEY.md §8.4
mode 1).

This replaces the reference's scheduleOne hot path
(pkg/scheduler/schedule_one.go#schedulePod -> findNodesThatFitPod ->
prioritizeNodes -> selectHost) with one compiled program: each scan step is a
dense filter-mask + score over ALL nodes at once (the per-(pod,node) Go
interface-call overhead becomes one fused XLA loop body), and the
assume-pod state mutation (cache.AssumePod) becomes an in-carry scatter so
the next step sees updated node state — preserving the reference's strict
pod-by-pod sequential semantics, which is what "binding parity" means.

Filter pipeline per step (runtime/framework.go#RunFilterPlugins, fused):
  NodeResourcesFit ∧ static class mask (NodeName ∧ NodeUnschedulable ∧
  TaintToleration ∧ NodeAffinity, precompiled per pod class) ∧ NodePorts
  (occupancy matvec over the port vocab) ∧ PodTopologySpread hard
  constraints (segment reductions over domain ids).

Score pipeline (runtime/framework.go#RunScorePlugins: score, normalize,
weight — default-profile weights from apis/config/v1/default_plugins.go):
  1·LeastAllocated + 1·BalancedAllocation + 3·TaintToleration(norm reverse)
  + 2·NodeAffinity(norm) + 1·ImageLocality + 2·PodTopologySpread(norm).

selectHost tie-break: the reference reservoir-samples uniformly among
max-score ties with an unseeded RNG (schedule_one.go#selectHost). Bit-parity
is impossible; we offer:
- "random": uniform among ties from a seeded PRNG key (documented divergence)
- "first":  lowest node index among ties (deterministic, used by parity tests)
Either way the pick is provably inside the reference's tie set, which is the
parity definition from SURVEY.md §8.8.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import interpod as ip
from ..ops import noderesources as nr
from ..ops import plugins as pl
from ..ops import spread as sp
from ..tensorize.interpod import InterpodTensors, trivial_interpod_tensors
from ..tensorize.plugins import (
    PortTensors,
    StaticPluginTensors,
    trivial_port_tensors,
    trivial_static_tensors,
)
from ..tensorize.spread import SpreadTensors, trivial_spread_tensors
from ..tensorize.schema import MEM_IDX, NodeBatch, PodBatch

TIE_RANDOM = "random"
TIE_FIRST = "first"


@dataclass(frozen=True)
class ExactSolverConfig:
    tie_break: str = TIE_RANDOM
    seed: int = 0
    # Score-plugin weights; defaults mirror the default profile
    # (apis/config/v1/default_plugins.go): TaintToleration 3, NodeAffinity 2,
    # PodTopologySpread 2, Fit/Balanced/ImageLocality 1.
    fit_weight: int = 1
    balanced_weight: int = 1
    # NodeResourcesFitArgs.scoringStrategy.type: LeastAllocated (default) |
    # MostAllocated (RequestedToCapacityRatio has kernel+oracle support in
    # ops/noderesources; shape plumbing lands with per-resource weights)
    scoring_strategy: str = "LeastAllocated"
    taint_weight: int = 3
    node_affinity_weight: int = 2
    image_weight: int = 1
    spread_weight: int = 2
    interpod_weight: int = 2
    # InterPodAffinityArgs.hardPodAffinityWeight (default 1) — consumed by
    # the interpod tensorizer when building m_w rows (the scheduler passes
    # it through to build_interpod_tensors)
    hard_pod_affinity_weight: int = 1
    balanced_fdtype: str = "float32"  # float64 for bit-parity on CPU tests


def _solve_scan(
    tables,  # dict of read-only node/class tables (see ExactSolver.solve)
    state0,  # dict of carried node state (donated)
    xs,  # dict of per-pod scanned inputs, leading axis P
    key,  # PRNG key
    *,
    tie_break: str,
    scoring_strategy: str,
    w_fit: int,
    w_balanced: int,
    w_taint: int,
    w_nodeaff: int,
    w_image: int,
    w_spread: int,
    w_interpod: int,
    use_spread: bool,
    use_interpod: bool,
    d_pad: int,
    ipa_d_pad: int,
    fdtype,
):
    alloc = tables["alloc"]
    alloc2 = alloc[: MEM_IDX + 1]  # cpu, memory rows for scoring
    weights2 = jnp.ones(2, dtype=alloc.dtype)
    spr = tables.get("spr")
    ipa = tables.get("ipa")

    def step(carry, x):
        st, k = carry
        cls = x["class_of"]

        mask = (
            nr.fit_mask(
                x["req"], x["req_mask"], alloc, st["used"],
                st["pod_count"], tables["max_pods"],
            )
            & tables["static_mask"][cls]
            & tables["node_valid"]
            & ~pl.ports_conflict_mask(x["pod_conflict"], st["port_used"])
        )
        if use_spread:
            mask = mask & ~sp.hard_violations(spr, st["spr_cnt"], cls, d_pad)
        if use_interpod:
            ipa_allowed, ipa_raw = ip.filter_and_score(
                ipa, st["ipa_in"], st["ipa_ex"], cls, x, ipa_d_pad,
                tables["node_valid"],
            )
            mask = mask & ipa_allowed

        requested = nr.scoring_requested(x["nonzero_req"], st["nonzero_used"])
        fit_scorer = (
            nr.most_allocated_score
            if scoring_strategy == "MostAllocated"
            else nr.least_allocated_score
        )
        score = w_fit * fit_scorer(requested, alloc2, weights2)
        score = score + w_balanced * nr.balanced_allocation_score(
            requested, alloc2, fdtype=fdtype
        )
        score = score.astype(jnp.int32)
        if w_taint:
            score = score + w_taint * pl.normalize_score(
                tables["taint_cnt"][cls], mask, reverse=True
            )
        if w_nodeaff:
            score = score + w_nodeaff * pl.normalize_score(
                tables["nodeaff_pref"][cls], mask, reverse=False
            )
        if w_image:
            score = score + w_image * tables["image_score"][cls]
        if use_spread and w_spread:
            score = score + w_spread * sp.soft_scores(
                spr, st["spr_cnt"], cls, mask, d_pad, fdtype=fdtype
            )
        if use_interpod and w_interpod:
            score = score + w_interpod * ip.normalize(ipa_raw, mask)
        score = jnp.where(mask, score, -1)

        best = jnp.max(score)
        feasible = best >= 0
        ties = (score == best) & mask
        csum = jnp.cumsum(ties)
        if tie_break == TIE_RANDOM:
            k, sub = jax.random.split(k)
            n_ties = csum[-1]
            pick_rank = jax.random.randint(sub, (), 0, jnp.maximum(n_ties, 1))
        else:
            pick_rank = 0
        pick = jnp.argmax(csum > pick_rank).astype(jnp.int32)

        found = feasible & x["pod_valid"]
        d = found.astype(alloc.dtype)
        di = found.astype(jnp.int32)
        st = dict(
            used=st["used"].at[:, pick].add(x["req"] * d),
            nonzero_used=st["nonzero_used"].at[:, pick].add(x["nonzero_req"] * d),
            pod_count=st["pod_count"].at[pick].add(di),
            port_used=st["port_used"].at[:, pick].add(x["pod_takes"] * di),
            spr_cnt=(
                st["spr_cnt"].at[:, pick].add(x["spr_placed"].astype(jnp.int32) * di)
                if use_spread
                else st["spr_cnt"]
            ),
            ipa_in=(
                st["ipa_in"].at[:, pick].add(x["ipa_in_match"] * di)
                if use_interpod
                else st["ipa_in"]
            ),
            ipa_ex=(
                st["ipa_ex"].at[:, pick].add(x["ipa_ex_owned"] * di)
                if use_interpod
                else st["ipa_ex"]
            ),
        )
        assignment = jnp.where(found, pick, -1).astype(jnp.int32)
        return (st, k), assignment

    (state, _), assignments = jax.lax.scan(step, (state0, key), xs)
    return assignments, state


_solve_scan_jit = jax.jit(
    _solve_scan,
    static_argnames=(
        "tie_break",
        "scoring_strategy",
        "w_fit",
        "w_balanced",
        "w_taint",
        "w_nodeaff",
        "w_image",
        "w_spread",
        "w_interpod",
        "use_spread",
        "use_interpod",
        "d_pad",
        "ipa_d_pad",
        "fdtype",
    ),
    donate_argnums=(1,),
)


class ExactSolver:
    """Host-facing wrapper: NodeBatch/PodBatch (+ plugin tensors) in,
    assignments out, node state written back (the device-side 'assume')."""

    def __init__(self, config: ExactSolverConfig | None = None):
        self.config = config or ExactSolverConfig()
        self._step_count = 0
        # int64 resource arithmetic is non-negotiable (memory bytes overflow
        # int32); jax 0.9+axon ignores the JAX_ENABLE_X64 env var, so enable
        # it here rather than trusting the embedding application.
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)

    def solve(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors | None = None,
        ports: PortTensors | None = None,
        spread: SpreadTensors | None = None,
        interpod: InterpodTensors | None = None,
    ) -> np.ndarray:
        """Returns assignments [num_pods] of node indices (-1 = unschedulable)
        and updates ``nodes``' used/nonzero_used/pod_count in place.

        Without ``static``/``ports``/``spread``/``interpod`` tensors, a
        trivial single-class mask (valid ∧ schedulable) reproduces the
        resources-only pipeline.
        """
        cfg = self.config
        fdtype = jnp.float64 if cfg.balanced_fdtype == "float64" else jnp.float32
        key = jax.random.PRNGKey(cfg.seed + self._step_count)
        self._step_count += 1
        if static is None:
            static = trivial_static_tensors(pods, nodes.padded, nodes.schedulable)
        if ports is None:
            ports = trivial_port_tensors(pods, nodes.padded)
        if spread is None:
            spread = trivial_spread_tensors(pods, nodes.padded, static.c_pad)
        if interpod is None:
            interpod = trivial_interpod_tensors(pods, nodes.padded, static.c_pad)
        use_spread = not spread.empty
        use_interpod = not interpod.empty

        tables = {
            "alloc": jnp.asarray(nodes.allocatable),
            "max_pods": jnp.asarray(nodes.max_pods),
            "node_valid": jnp.asarray(nodes.valid),
            "static_mask": jnp.asarray(static.mask),
            "taint_cnt": jnp.asarray(static.taint_cnt),
            "nodeaff_pref": jnp.asarray(static.nodeaff_pref),
            "image_score": jnp.asarray(static.image_score),
            "spr": {
                "dom": jnp.asarray(spread.dom),
                "elig": jnp.asarray(spread.elig),
                "max_skew": jnp.asarray(spread.max_skew),
                "min_domains": jnp.asarray(spread.min_domains),
                "self_match": jnp.asarray(spread.self_match),
                "is_hostname": jnp.asarray(spread.is_hostname),
                "hard": jnp.asarray(spread.hard),
                "soft": jnp.asarray(spread.soft),
            },
            "ipa": {
                "in_dom": jnp.asarray(interpod.in_dom),
                "in_pref_w": jnp.asarray(interpod.in_pref_w),
                "cls_req_aff": jnp.asarray(interpod.cls_req_aff),
                "cls_req_anti": jnp.asarray(interpod.cls_req_anti),
                "cls_pref": jnp.asarray(interpod.cls_pref),
                "ex_dom": jnp.asarray(interpod.ex_dom),
                "ex_anti": jnp.asarray(interpod.ex_anti),
            },
        }
        state0 = {
            "used": jnp.asarray(nodes.used),
            "nonzero_used": jnp.asarray(nodes.nonzero_used),
            "pod_count": jnp.asarray(nodes.pod_count),
            "port_used": jnp.asarray(ports.used),
            "spr_cnt": jnp.asarray(spread.cnt0),
            "ipa_in": jnp.asarray(interpod.in_cnt0),
            "ipa_ex": jnp.asarray(interpod.ex_cnt0),
        }
        xs = {
            "req": jnp.asarray(pods.req),
            "req_mask": jnp.asarray(pods.req_mask),
            "nonzero_req": jnp.asarray(pods.nonzero_req),
            "pod_valid": jnp.asarray(pods.valid & pods.feasible_static),
            "class_of": jnp.asarray(static.class_of),
            "pod_conflict": jnp.asarray(ports.pod_conflict),
            "pod_takes": jnp.asarray(ports.pod_takes),
            "spr_placed": jnp.asarray(spread.placed_match),
            "ipa_in_match": jnp.asarray(interpod.in_match),
            "ipa_ex_owned": jnp.asarray(interpod.ex_owned),
            "ipa_m_anti": jnp.asarray(interpod.m_anti),
            "ipa_m_w": jnp.asarray(interpod.m_w),
            "ipa_self_aff": jnp.asarray(interpod.self_aff),
        }
        assignments, state = _solve_scan_jit(
            tables,
            state0,
            xs,
            key,
            tie_break=cfg.tie_break,
            scoring_strategy=cfg.scoring_strategy,
            w_fit=cfg.fit_weight,
            w_balanced=cfg.balanced_weight,
            w_taint=cfg.taint_weight,
            w_nodeaff=cfg.node_affinity_weight,
            w_image=cfg.image_weight,
            w_spread=cfg.spread_weight,
            w_interpod=cfg.interpod_weight,
            use_spread=use_spread,
            use_interpod=use_interpod,
            d_pad=spread.d_pad,
            ipa_d_pad=interpod.d_pad,
            fdtype=fdtype,
        )
        # np.array(copy=True): np.asarray on a jax array yields a READ-ONLY
        # view, which would freeze the snapshot's dirty-column writes
        nodes.used = np.array(state["used"])
        nodes.nonzero_used = np.array(state["nonzero_used"])
        nodes.pod_count = np.array(state["pod_count"])
        return np.asarray(assignments)[: pods.num_pods]
