"""Single-shot assignment solver — SURVEY.md §8.4 mode 2, the engine for
the 50k-pods x 10k-nodes rebalance target (BASELINE.md north star).

The exact scan preserves pod-by-pod sequential semantics but pays one
scan-step of latency per pod; at 50k pods that serial chain dominates. The
single-shot mode trades sequential parity for parallelism (the documented
divergence from SURVEY §8.4): an auction-style capacity-constrained
assignment where every round is dense work over ALL pods at once:

  1. pods dedup into REQUEST CLASSES (static-plugin class + request
     vector); feasibility and scoring are [RC, N] tables, never [P, N] —
     the memory move that makes 50k x 10k fit in HBM;
  2. each class bids on its top-T feasible nodes by
     score - price (price = congestion penalty raised on rejection, the
     Bertsekas-auction analog); pods of a class fan out round-robin over
     the class's top-T so one round can fill many nodes in parallel;
  3. claimants are admitted per node in priority order under the node's
     remaining resources: sort by (node, -priority), per-resource segment
     prefix sums admit the largest feasible prefix — the dense equivalent
     of the reference's one-at-a-time assume loop;
  4. admitted pods commit via scatter-add; the rest re-bid next round.

Rounds run inside one jitted lax.scan (fixed max_rounds; converged rounds
are no-ops): sort + segment reductions + gathers, no host round-trips.

After the top-T loop a FULL-WIDTH REPAIR phase closes the scarcity gap
(SURVEY §8.4 / VERDICT missing #6): under contention the fullest nodes
carry low headroom scores, fall outside every class's top-T window, and
their prices never escalate — so small remaining gaps on them stay
invisible and capacity strands (measured: scarce_rc8 placed_ratio
0.9854). The repair reruns the same auction round with the bid window
widened to ALL nodes, and keeps going while anyone still *bids* (placed
OR rejected > 0 — a rejected bid escalated a price, so the next round
explores a different node), bounded by ``repair_rounds``. Work
conservation then holds up to the round budget: a pod is left unplaced
only when no feasible node remains anywhere. Solves that already placed
everything skip the phase in one condition check.

``objective`` flips the score sense: ``"spread"`` (default) prefers
high-headroom nodes — the serving posture; ``"pack"`` prefers FULL
nodes — the bin-packing posture the continuous rebalancer
(kubernetes_tpu/rebalance) plans consolidation targets with.

Scope: NodeResourcesFit + the static per-class plugin mask (taints,
affinity, nodeName, unschedulable) + headroom scoring vs the snapshot.
Ports/spread/interpod route through the exact scan path instead.

Validated properties (tests): feasibility of every placement, work
conservation (unplaced only when nothing feasible remains), and priority
dominance under scarcity.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..tensorize.plugins import StaticPluginTensors, trivial_static_tensors
from ..tensorize.schema import CPU_IDX, MEM_IDX, NodeBatch, PodBatch

NEG = jnp.int32(-(1 << 30))

CUMSUM_BLOCK = 512


def _cumsum0(x, block: int = CUMSUM_BLOCK):
    """Two-level cumsum along axis 0. XLA lowers a monolithic cumsum over a
    50k axis to one giant reduce-window whose scoped VMEM blows the 16M
    limit on v5e; blocking it (intra-block cumsum + block-offset cumsum)
    keeps every window small."""
    p = x.shape[0]
    if p <= block:
        return jnp.cumsum(x, axis=0)
    pb = ((p + block - 1) // block) * block
    pad = pb - p
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad, *x.shape[1:]), x.dtype)], axis=0
        )
    xb = x.reshape(pb // block, block, *x.shape[1:])
    within = jnp.cumsum(xb, axis=1)
    row_tot = within[:, -1]
    offs = jnp.cumsum(row_tot, axis=0) - row_tot
    out = within + offs[:, None]
    return out.reshape(pb, *x.shape[1:])[:p]


@dataclass(frozen=True)
class SingleShotConfig:
    max_rounds: int = 32
    # price escalation per rejection round, in score points
    price_step: int = 8
    # nodes each request-class fans out over per round (clamped to N);
    # wider = fewer rounds: 1024 measured 189ms vs 320ms at 256 for the
    # 51.2k x 10.24k north-star config on v5e
    top_t: int = 1024
    # full-width repair rounds after the top-T loop (the scarcity
    # closer: nodes outside every top-T window become biddable). 0
    # disables — restoring the pre-repair early-exit behavior.
    repair_rounds: int = 16
    # "spread" = prefer high-headroom nodes (serving default);
    # "pack" = prefer full nodes (the rebalancer's consolidation plan)
    objective: str = "spread"


def _segmented_prefix(x, seg_start, seg_id, num_segments):
    """Inclusive prefix sum of ``x`` within segments of a sorted key.
    x: [P] or [P, K]; seg_start: [P] bool; seg_id: [P] int32."""
    csum = _cumsum0(x)
    base_at_start = jnp.where(
        seg_start if x.ndim == 1 else seg_start[:, None], csum - x, 0
    )
    seg_base = jax.ops.segment_max(
        base_at_start, seg_id, num_segments=num_segments
    )
    return csum - seg_base[seg_id]


def _single_shot(
    alloc,  # [K, N] int
    used0,  # [K, N] int
    pod_count0,  # [N] int32
    max_pods,  # [N] int32
    node_valid,  # [N] bool
    static_mask,  # [C, N] bool
    rc_req,  # [RC, K] int — request per request-class
    rc_static,  # [RC] int32 — static-plugin class of the request-class
    rc_of,  # [P] int32
    priority,  # [P] int32
    pod_valid,  # [P] bool
    *,
    max_rounds: int,
    price_step: int,
    top_t: int,
    repair_rounds: int = 16,
    pack: bool = False,
):
    p = rc_of.shape[0]
    n = alloc.shape[1]
    k = alloc.shape[0]
    rc = rc_req.shape[0]
    t = min(top_t, n)

    alloc2 = alloc[: MEM_IDX + 1].astype(jnp.float32)
    used2 = used0[: MEM_IDX + 1].astype(jnp.float32)
    free_frac = jnp.where(
        alloc2 > 0, (alloc2 - used2) / jnp.maximum(alloc2, 1.0), 0.0
    )
    headroom = (
        100.0 * (free_frac[CPU_IDX] + free_frac[MEM_IDX]) / 2.0
    ).astype(jnp.int32)  # [N] headroom at snapshot
    # pack objective inverts the preference: full nodes score high, so
    # the auction consolidates instead of spreading (the rebalancer's
    # planning posture). Same integer arithmetic — still deterministic.
    base_score = (jnp.int32(100) - headroom) if pack else headroom

    pod_idx = jnp.arange(p, dtype=jnp.int32)

    def make_round(t_r: int):
        """One auction round bidding over each class's top ``t_r``
        feasible nodes. The main loop uses t_r = top_t; the repair phase
        re-instantiates with t_r = n (every node biddable)."""

        def round_step(carry):
            used, pod_count, price, assigned_to = carry
            unassigned = (assigned_to < 0) & pod_valid

            # 1. class-level feasibility on REMAINING capacity: [RC, N]
            free = alloc - used
            fit = jnp.all(
                rc_req[:, :, None] <= free[None, :, :], axis=1
            )  # [RC, K, N] -> [RC, N]; RC is small by construction
            ok = (
                fit
                & static_mask[rc_static]
                & node_valid[None, :]
                & (pod_count + 1 <= max_pods)[None, :]
            )
            score = jnp.where(ok, base_score[None, :] - price[None, :], NEG)

            # 2. top-T nodes per class + round-robin fan-out of the
            # class's unassigned pods across them
            top_scores, top_nodes = jax.lax.top_k(score, t_r)  # [RC, T]
            top_ok = top_scores > NEG
            # feasible entries sort to the front; fan out only across them
            # so a class with few feasible nodes still bids every round
            n_ok = jnp.sum(top_ok.astype(jnp.int32), axis=1)  # [RC]

            # rank of each unassigned pod within its class (stable)
            key = jnp.where(
                unassigned, rc_of.astype(jnp.int64) * p + pod_idx, (1 << 62)
            )
            order_rc = jnp.argsort(key)
            rc_sorted = rc_of[order_rc]
            seg_start_rc = jnp.concatenate(
                [jnp.array([True], dtype=jnp.bool_), rc_sorted[1:] != rc_sorted[:-1]]
            )
            seg_id_rc = _cumsum0(seg_start_rc.astype(jnp.int32)) - 1
            rank_sorted = (
                _segmented_prefix(
                    jnp.ones(p, dtype=jnp.int32), seg_start_rc, seg_id_rc, p
                )
                - 1
            )
            rank = jnp.zeros(p, dtype=jnp.int32).at[order_rc].set(rank_sorted)

            slot = rank % jnp.maximum(n_ok[rc_of], 1)
            target = top_nodes[rc_of, slot].astype(jnp.int32)
            has_node = n_ok[rc_of] > 0
            bidding = unassigned & has_node
            target = jnp.where(bidding, target, n)  # park at virtual node n

            # 3. admission: sort claimants by (node, -priority), segmented
            # prefix sums against the node's remaining resources. The
            # inverted priority is biased into [0, 2^32) so the full legal
            # int32 priority range (system-critical 2e9 down to very
            # negative user values) packs below the node id without
            # interleaving adjacent nodes.
            inv_prio = jnp.int64((1 << 31) - 1) - priority.astype(jnp.int64)
            sort_key = target.astype(jnp.int64) * (1 << 32) + inv_prio
            order = jnp.argsort(sort_key)
            t_sorted = target[order]
            bidding_sorted = bidding[order]
            req_sorted = jnp.where(
                bidding_sorted[:, None], rc_req[rc_of[order]], 0
            )  # [P, K]

            seg_start = jnp.concatenate(
                [jnp.array([True], dtype=jnp.bool_), t_sorted[1:] != t_sorted[:-1]]
            )
            seg_id = _cumsum0(seg_start.astype(jnp.int32)) - 1
            prefix = _segmented_prefix(req_sorted, seg_start, seg_id, p)
            cnt_prefix = _segmented_prefix(
                bidding_sorted.astype(jnp.int32), seg_start, seg_id, p
            )

            free_t = jnp.concatenate([free, jnp.zeros((k, 1), free.dtype)], axis=1)
            cnt_free = jnp.concatenate(
                [(max_pods - pod_count).astype(jnp.int32), jnp.zeros(1, jnp.int32)]
            )
            fits_res = jnp.all(prefix <= free_t[:, t_sorted].T, axis=1)
            fits_cnt = cnt_prefix <= cnt_free[t_sorted]
            admit_sorted = bidding_sorted & fits_res & fits_cnt
            admit = jnp.zeros(p, dtype=bool).at[order].set(admit_sorted)

            # 4. commit + price escalation on rejection
            assigned_to = jnp.where(admit, target, assigned_to)
            tgt_or_park = jnp.where(admit, target, n)
            used = used + jax.ops.segment_sum(
                jnp.where(admit[:, None], rc_req[rc_of], 0),
                tgt_or_park,
                num_segments=n + 1,
            )[:n].T
            pod_count = pod_count + jax.ops.segment_sum(
                admit.astype(jnp.int32), tgt_or_park, num_segments=n + 1
            )[:n]
            rejected = bidding & ~admit
            rej_per_node = jax.ops.segment_sum(
                rejected.astype(jnp.int32), jnp.where(rejected, target, n),
                num_segments=n + 1,
            )[:n]
            price = price + jnp.where(rej_per_node > 0, price_step, 0)

            return (
                (used, pod_count, price, assigned_to),
                admit.sum().astype(jnp.int32),
                rejected.sum().astype(jnp.int32),
            )

        return round_step

    main_round = make_round(t)
    assigned0 = jnp.full(p, -1, dtype=jnp.int32)
    price0 = jnp.zeros(n, dtype=jnp.int32)

    # while_loop with early exit: converged solves stop paying for the
    # remaining round budget (placed==0 means no further progress possible
    # at this bid width — every still-unassigned pod found no feasible
    # top-T node or lost admission AND prices already escalated; the
    # repair phase below re-examines with the window fully open)
    def cond(state):
        rounds, last_placed, _ = state
        return (rounds < max_rounds) & (last_placed > 0)

    def body(state):
        rounds, _, carry = state
        carry, placed, _rejected = main_round(carry)
        return rounds + 1, placed, carry

    init_placed = jnp.int32(1)
    main_rounds, _, carry = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init_placed, (used0, pod_count0, price0, assigned0))
    )
    rounds_total = main_rounds

    if repair_rounds > 0 and p > 0:
        # full-width repair: every feasible node is biddable, and the
        # loop keeps going while anyone still BIDS — a round that placed
        # nothing but rejected someone escalated that node's price, so
        # the next round explores a different node. Terminates when no
        # unassigned pod has any feasible node left (nobody bids).
        repair_round = make_round(n)

        def cond_rep(state):
            rounds, bid_activity, carry_r = state
            _, _, _, assigned_to = carry_r
            remaining = jnp.any((assigned_to < 0) & pod_valid)
            return (rounds < repair_rounds) & bid_activity & remaining

        def body_rep(state):
            rounds, _, carry_r = state
            carry_r, placed, rejected = repair_round(carry_r)
            return rounds + 1, (placed + rejected) > 0, carry_r

        rep_rounds, _, carry = jax.lax.while_loop(
            cond_rep, body_rep, (jnp.int32(0), jnp.bool_(True), carry)
        )
        rounds_total = rounds_total + rep_rounds

    used, pod_count, _, assigned_to = carry
    placed_total = jnp.sum((assigned_to >= 0).astype(jnp.int32))
    return assigned_to, used, pod_count, placed_total, rounds_total


_single_shot_jit = jax.jit(
    _single_shot,
    static_argnames=(
        "max_rounds", "price_step", "top_t", "repair_rounds", "pack",
    ),
    donate_argnums=(1, 2),
)


def request_classes(
    pods: PodBatch, static: StaticPluginTensors
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dedup (static class, request vector) -> (rc_req [RC, K],
    rc_static [RC], rc_of [Pp])."""
    keyed = np.concatenate(
        [static.class_of[:, None].astype(np.int64), pods.req], axis=1
    )
    uniq, inverse = np.unique(keyed, axis=0, return_inverse=True)
    rc_static = uniq[:, 0].astype(np.int32)
    rc_req = uniq[:, 1:].astype(pods.req.dtype)
    return rc_req, rc_static, inverse.astype(np.int32)


class SingleShotSolver:
    """Host wrapper mirroring ExactSolver.solve's contract (fit + static
    mask scope)."""

    def __init__(self, config: SingleShotConfig | None = None):
        self.config = config or SingleShotConfig()
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)

    def solve(
        self,
        nodes: NodeBatch,
        pods: PodBatch,
        static: StaticPluginTensors | None = None,
        mesh=None,
    ) -> np.ndarray:
        """``mesh``: an optional jax.sharding.Mesh with a "nodes" axis — the
        v5e-8 path (SURVEY §6.7): every node-resident array shards over its
        trailing node axis, pod/class arrays replicate, and GSPMD inserts
        the cross-shard collectives (top-k, segment admission) the auction
        rounds need. Same numerics as the single-chip path — integer score
        arithmetic and stable sorts make the result device-count-invariant
        (tests/test_sharding.py asserts bit-equality on an 8-way mesh)."""
        if static is None:
            static = trivial_static_tensors(pods, nodes.padded, nodes.schedulable)
        # index-dtype audit (solver/budget.py): the admission sort key
        # (target << 32 + inv_prio) and the class-rank key (rc * P +
        # idx) must fit int64 at this shape — typed failure at dispatch
        # instead of a silent device-side wrap at 2^31-scale inputs
        from .budget import assert_index_headroom

        assert_index_headroom(pods.padded, nodes.padded)
        rc_req, rc_static, rc_of = request_classes(pods, static)
        args = [
            nodes.allocatable,
            nodes.used,
            nodes.pod_count,
            nodes.max_pods,
            nodes.valid,
            static.mask,
            rc_req,
            rc_static,
            rc_of,
            pods.priority,
            pods.valid & pods.feasible_static,
        ]
        if mesh is not None:
            from ..parallel.sharding import node_sharding, replicated

            node_axis_args = {0, 1, 2, 3, 4, 5}  # node-resident inputs
            args = [
                jax.device_put(
                    jnp.asarray(a),
                    node_sharding(mesh, np.ndim(a))
                    if i in node_axis_args
                    else replicated(mesh),
                )
                for i, a in enumerate(args)
            ]
        else:
            args = [jnp.asarray(a) for a in args]
        assigned, used, pod_count, _, _ = _single_shot_jit(
            *args,
            max_rounds=self.config.max_rounds,
            price_step=self.config.price_step,
            top_t=self.config.top_t,
            repair_rounds=self.config.repair_rounds,
            pack=self.config.objective == "pack",
        )
        nodes.used = np.array(used)
        nodes.pod_count = np.array(pod_count)
        return np.asarray(assigned)[: pods.num_pods]
