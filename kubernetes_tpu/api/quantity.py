"""Kubernetes resource.Quantity parsing to canonical fixed-point integers.

Reference semantics: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go
(`Quantity.Value()`, `Quantity.MilliValue()`, suffix handling in suffix.go).

The reference keeps arbitrary-precision decimal quantities and converts lazily.
TPU kernels need fixed-point int64, so we convert eagerly at the API boundary:

- ``cpu``                    -> integer *milli*-cores  (``MilliValue()``)
- ``memory``/``*storage*``   -> integer bytes          (``Value()``)
- everything else (pods, hugepages, extended resources) -> integer units
  (``Value()``)

Rounding matches the reference: values scale *up* (ceiling away from zero),
so "0.5m" CPU becomes 1 milli-unit, "1.5" bytes becomes 2 bytes
(quantity.go#Value rounds up via ScaledValue/infDecAmount.AsScale).

Values are saturated to int64 range; overflow is impossible downstream.
"""

from __future__ import annotations

import re
from fractions import Fraction

MAX_INT64 = (1 << 63) - 1
MIN_INT64 = -(1 << 63)

# Binary SI (Ki=1024^1 ...) and decimal SI suffixes, per
# apimachinery/pkg/api/resource/suffix.go#fastLookup.
_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<digits>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:[eE](?P<exp>[+-]?[0-9]+)|(?P<suffix>[a-zA-Z]{1,2}))?$"
)


class QuantityError(ValueError):
    """Raised for malformed quantity strings."""


def parse_quantity(s: str | int | float) -> Fraction:
    """Parse a Kubernetes quantity into an exact Fraction of base units."""
    if isinstance(s, int):
        return Fraction(s)
    if isinstance(s, float):
        return Fraction(s).limit_denominator(10**9)
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise QuantityError(f"invalid quantity: {s!r}")
    digits = m.group("digits")
    value = Fraction(digits)
    if m.group("sign") == "-":
        value = -value
    exp = m.group("exp")
    suffix = m.group("suffix")
    if exp is not None:
        e = int(exp)
        value *= Fraction(10) ** e
    elif suffix:
        if suffix in _BINARY_SUFFIXES:
            value *= _BINARY_SUFFIXES[suffix]
        elif suffix in _DECIMAL_SUFFIXES:
            value *= _DECIMAL_SUFFIXES[suffix]
        else:
            raise QuantityError(f"invalid suffix in quantity: {s!r}")
    return value


def _ceil(f: Fraction) -> int:
    # Quantity.Value()/MilliValue() round up (toward +inf). Negative resource
    # quantities are rejected by API validation, so ceiling is safe everywhere.
    n, d = f.numerator, f.denominator
    q = n // d
    return q if n % d == 0 else q + 1


def _saturate(v: int) -> int:
    return max(MIN_INT64, min(MAX_INT64, v))


def quantity_value(s: str | int | float) -> int:
    """Integer base units, rounding up — Quantity.Value()."""
    return _saturate(_ceil(parse_quantity(s)))


def quantity_milli_value(s: str | int | float) -> int:
    """Integer milli-units, rounding up — Quantity.MilliValue()."""
    return _saturate(_ceil(parse_quantity(s) * 1000))


def canonical(resource_name: str, s: str | int | float) -> int:
    """Canonical int for a named resource: cpu -> milli, otherwise -> Value().

    Mirrors how the scheduler reads quantities in
    pkg/scheduler/framework/types.go#Resource.Add (MilliCPU vs Value).
    """
    if resource_name == "cpu":
        return quantity_milli_value(s)
    return quantity_value(s)


def canonical_requests(raw: dict[str, str | int | float] | None) -> dict[str, int]:
    """Canonicalize a resource map (e.g. container requests)."""
    if not raw:
        return {}
    return {k: canonical(k, v) for k, v in raw.items()}


def format_canonical(resource_name: str, v: int) -> str:
    """Format a canonical int back to a wire quantity string."""
    if resource_name == "cpu":
        if v % 1000 == 0:
            return str(v // 1000)
        return f"{v}m"
    return str(v)
