"""ISSUE 18 satellite: the bench JSON's top level must keep carrying
every quality-bar key ROADMAP.md owes the driver-captured ladder.

The standing quality bar says flagship features land in the bench
ladder as HOISTED top-level keys (the driver snapshots the JSON top
level; a number buried inside a ladder dict is invisible to it). The
hoists accreted one PR at a time, which makes them easy to lose in a
refactor of ``bench.py main()`` — and a silently-dropped hoist reads
as a feature regression in the next snapshot. This test pins the
contract STATICALLY: AST-scan ``main()`` for literal dict keys, no
bench execution (the real ladders take minutes and need hardware-ish
timing; the contract being tested is about the JSON shape, not the
numbers).
"""

from __future__ import annotations

import ast
from pathlib import Path

BENCH = Path(__file__).resolve().parent.parent / "bench.py"

# every top-level key the ROADMAP quality bar owes the driver capture:
# the PR 6-10 flagship families + the PRs 11-18 hoists, in PR order
OWED_KEYS = {
    # sustained-arrival ladder (#6)
    "sustained_pods_per_sec",
    "sustained_p99_pod_latency_s",
    # streaming dispatcher (#6/#10)
    "streaming_speedup",
    "streaming_p99_pod_latency_s",
    "streaming_unhidden_reads_per_batch",
    # node-axis multichip sharding (#8, device tiers)
    "multichip_pods_per_sec",
    "multichip_speedup",
    # fleet scale-out (#8/PR 11)
    "fleet_pods_per_sec",
    "fleet_speedup",
    # resilience ladder (#9): forced host-greedy degraded arm
    "degraded_pods_per_sec",
    # continuous rebalancer (#10)
    "rebalance_utilization_gain",
    "rebalance_plan_solve_s",
    # 512k backlog drain (PR 12, ladder #11)
    "backlog_drain_pods_per_sec",
    "backlog_drain_seconds",
    # closed-loop auto-tuning (PR 13, ladder #12)
    "tuned_pods_per_sec",
    "tuning_convergence_batches",
    # obs layer + live SLO engine (PR 14, ladder #13)
    "slo_p99_pod_latency_s",
    "obs_overhead_fraction",
    # hub HA failover (PR 15, ladder #14)
    "hub_failover_blackout_s",
    "hub_failover_p99_latency_s",
    # gang scheduling (PR 17, ladder #15)
    "gang_pods_per_sec",
    "gang_time_to_full_p99_s",
    # flight telemetry (PR 18, ladder #13 refresh)
    "profiler_overhead_fraction",
    "anomaly_detection_lag_batches",
    # convex-relaxation mega-planner (PR 19, ladder #16)
    "relax_plan_seconds",
    "relax_objective_ratio",
    "megaplan_pods_per_sec",
    # fleet-tier backlog drain (PR 20, ladder #17)
    "fleet_drain_pods_per_sec",
    "fleet_drain_speedup",
}


def _main_literal_str_keys() -> set:
    """Every literal string dict key inside bench.py's ``main()`` —
    the function that assembles the top-level JSON document."""
    tree = ast.parse(BENCH.read_text())
    main = next(
        n
        for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name == "main"
    )
    keys = set()
    for node in ast.walk(main):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
    return keys


def test_bench_main_hoists_every_owed_roadmap_key():
    keys = _main_literal_str_keys()
    missing = OWED_KEYS - keys
    assert not missing, (
        "bench.py main() no longer hoists these ROADMAP quality-bar "
        f"keys to the JSON top level: {sorted(missing)}"
    )


# hoists that deliberately RENAME their ladder-dict source key (the
# top-level name is the contract; the nested name predates it) — these
# legitimately appear only once in bench.py
RENAMED_AT_HOIST = {
    "streaming_speedup",  # <- streaming_p99_speedup_vs_pipelined
    "streaming_p99_pod_latency_s",  # nested under ["streaming"]
}


def test_owed_keys_have_no_typos_against_ladder_sources():
    """Each owed key must also appear SOMEWHERE in bench.py outside
    main() (the ladder that computes it) — catches a hoist that
    renames the source but keeps a stale literal in main(). Keys the
    hoist deliberately renames are allowlisted above; growing that
    set should be a conscious choice, not a drive-by."""
    src = BENCH.read_text()
    missing = {
        k
        for k in OWED_KEYS - RENAMED_AT_HOIST
        if src.count(f'"{k}"') < 2
    }
    assert not missing, (
        "these owed keys appear fewer than twice in bench.py (hoist + "
        f"ladder source): {sorted(missing)}"
    )
    for k in RENAMED_AT_HOIST:
        assert src.count(f'"{k}"') == 1, (
            f"{k} no longer looks renamed-at-hoist — update "
            "RENAMED_AT_HOIST"
        )
