"""Rebalance planning: auction target assignment + bounded move diff.

``plan_moves`` runs the single-shot auction (solver/single_shot.py)
with the ``pack`` objective over the current cluster: the candidate
pods — the movable residents of the emptiest in-use nodes, chosen by
the runtime up to the churn budget — re-bid against the cluster's LIVE
load with their source nodes masked out of the plan. Planning against
live load is what makes packing work: the fullest nodes carry the
highest pack scores, so the narrow-window auction consolidates onto
them. (Re-placing *everything* from a zeroed cluster was tried first
and scatters — with every node empty the pack objective has no
gradient and round 1 admits the whole population anywhere.) The target
assignment is then diffed against the actual placement (source-masked,
so every planned pod diffs) and ``select_moves`` bounds the raw diff
into an executable migration plan:

- **churn budget** — at most ``budget`` moves per cycle;
- **priority order** — least-important pods first (the inverse of
  ``MoreImportantPod``), best packing gain first within a priority;
- **strict improvement** — a move is kept only when the target node's
  dominant-resource fill (current truth) strictly exceeds the source's
  fill without the pod, by at least ``min_gain`` points: pods the plan
  cannot strictly improve are never touched, and each executed move
  strictly increases the cluster's packing potential, so repeated
  cycles terminate instead of thrashing;
- **joint feasibility** — moves are admitted against a working copy of
  the CURRENT free capacity (not the plan's hypothetical one), so every
  selected move is immediately executable no matter how few of the
  plan's other moves run this cycle;
- **PDB gate** — the selected stream passes through
  ``classify_pdb_violations`` (ops/oracle/preemption.py) in selection
  order, decrementing allowances per candidate exactly like
  ``filterPodsWithPDBViolation``; violating pods drop out (counted, not
  backfilled — their budget slot retries next cycle).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..api.objects import Pod
from ..ops.oracle.preemption import classify_pdb_violations
from ..solver.relax import RelaxConfig, RelaxSolver
from ..solver.single_shot import SingleShotConfig, SingleShotSolver
from ..tensorize.plugins import build_static_tensors, trivial_static_tensors
from ..tensorize.schema import NodeBatch, build_pod_batch
from .detector import packing_score


@dataclass(frozen=True)
class Move:
    pod: Pod
    source: str  # node name the pod is evicted from
    target: str  # node name the auction placed it on (nominated hint)
    source_slot: int
    target_slot: int
    gain: int  # packing-score improvement, percent points


@dataclass
class RebalancePlan:
    moves: list[Move] = field(default_factory=list)
    planned: int = 0  # raw target-vs-actual diff size before bounding
    pdb_blocked: int = 0  # selected moves dropped by the PDB gate


# the planner's auction posture: pack objective (fullest feasible nodes
# first) with a NARROW bid window — the round-robin fan-out spreads a
# class across its whole window, so a wide window would scatter instead
# of consolidate; 8 fullest nodes per round measured a good balance of
# rounds vs packing on the bench shapes
PLAN_TOP_T = 8


def plan_auction_config(base: SingleShotConfig | None = None) -> SingleShotConfig:
    base = base or SingleShotConfig()
    return SingleShotConfig(
        max_rounds=base.max_rounds,
        price_step=base.price_step,
        top_t=PLAN_TOP_T,
        # NO repair phase: full-width repair fans the unplaced tail out
        # across every feasible node — the wide-window scatter the
        # narrow top_t above exists to avoid. Work conservation is a
        # serving-solve property; for the consolidation plan an
        # unplaced candidate simply isn't moved this cycle.
        repair_rounds=0,
        objective="pack",
    )


# engine routing: below this pods x padded-nodes product the auction's
# sequential rounds are cheap and its narrow-window consolidation is
# the better plan; above it the relaxation's matmul iterations win the
# wall-clock race outright (bench ladder #16: >= 10x at 512k x 102k)
RELAX_PLAN_CELLS = 1 << 24


def plan_engine(n_pods: int, n_nodes_padded: int, engine: str = "auto") -> str:
    """Resolve the planning engine for a shape: ``"auction"`` or
    ``"relax"`` force it; ``"auto"`` routes by the pods x nodes cell
    count — the quantity both engines' dominant terms scale with."""
    if engine in ("auction", "relax"):
        return engine
    if engine != "auto":
        raise ValueError(f"unknown plan engine: {engine!r}")
    return (
        "relax"
        if n_pods * n_nodes_padded >= RELAX_PLAN_CELLS
        else "auction"
    )


def plan_moves(
    batch: NodeBatch,
    movable: list[tuple[Pod, int]],
    fixed_used: np.ndarray,
    fixed_cnt: np.ndarray,
    drain_slots: frozenset[int] = frozenset(),
    *,
    slot_nodes=None,
    auction: SingleShotConfig | None = None,
    engine: str = "auto",
    relax: RelaxConfig | None = None,
) -> list[tuple[Pod, int, int]]:
    """Target assignment for the candidate pods: the auction re-places
    them against the cluster's live load minus their own usage
    (``fixed_used``/``fixed_cnt``), with the drain-source slots masked
    unschedulable so the plan pushes OFF them. Returns the raw diff
    [(pod, source_slot, target_slot)] — pods the auction left unplaced
    (nowhere strictly feasible) are absent and never touched. ``batch``
    is read-only here; the auction runs against a copy.

    ``slot_nodes`` (Node-or-None per snapshot slot): when given, the
    production static plugin builder folds nodeSelector / node
    affinity / taints / nodeName into per-class masks, so a
    constrained pod is only ever planned toward a node it can actually
    run on — an infeasible target would otherwise evict the pod just
    for the real solve to bounce it back, a perpetual churn loop the
    strict-gain selection alone cannot prevent (the gain math is
    packing-only). Without ``slot_nodes`` (synthetic tensor callers,
    e.g. the bench) the mask degrades to schedulable-only.

    ``engine``: ``"auction"`` (the narrow-window pack auction),
    ``"relax"`` (the convex-relaxation mega-planner, solver/relax.py —
    relaxed solve, deterministic rounding, auction tail repair at the
    plan posture), or ``"auto"`` (route by shape via ``plan_engine``;
    churn-budget-sized candidate lists stay on the auction)."""
    if not movable:
        return []
    import dataclasses

    # the candidates are still BOUND while we plan (eviction comes
    # after bounding): strip the placement fields, or the static
    # builder's nodeName fold would pin every pod's class mask to its
    # current node and the plan could never move anything
    pods = [
        dataclasses.replace(p, node_name="", nominated_node_name="")
        for p, _ in movable
    ]
    pbatch = build_pod_batch(pods, batch.vocab)
    schedulable = batch.schedulable.copy()
    for slot in drain_slots:
        schedulable[slot] = False
    plan_nodes = NodeBatch(
        vocab=batch.vocab,
        names=list(batch.names),
        num_nodes=batch.num_nodes,
        padded=batch.padded,
        allocatable=batch.allocatable.copy(),
        used=fixed_used.copy(),
        nonzero_used=fixed_used[:2].copy(),
        pod_count=fixed_cnt.copy(),
        max_pods=batch.max_pods.copy(),
        valid=batch.valid.copy(),
        schedulable=schedulable,
    )
    if slot_nodes is not None:
        static = build_static_tensors(
            pods, pbatch, slot_nodes, batch.padded
        )
        live = (batch.valid & schedulable)[: batch.padded]
        static.mask &= live[None, :]
    else:
        static = trivial_static_tensors(
            pbatch, batch.padded, batch.valid & schedulable
        )
    chosen = plan_engine(len(pods), batch.padded, engine)
    if chosen == "relax":
        # mega-plan posture: pack-objective relaxation, then the SAME
        # plan auction config repairs the integrality tail (narrow
        # window, no repair phase) so the end state keeps the
        # consolidation bias and the auction's feasibility guarantees
        cfg = relax or RelaxConfig()
        if cfg.objective != "pack":
            cfg = dataclasses.replace(cfg, objective="pack")
        assigned = RelaxSolver(
            cfg, repair=plan_auction_config(auction)
        ).solve(plan_nodes, pbatch, static)
    else:
        assigned = SingleShotSolver(plan_auction_config(auction)).solve(
            plan_nodes, pbatch, static
        )
    out: list[tuple[Pod, int, int]] = []
    for i, (pod, src) in enumerate(movable):
        dst = int(assigned[i])
        if dst >= 0 and dst != src:
            out.append((pod, src, dst))
    return out


def select_moves(
    batch: NodeBatch,
    slot_names: list[str],
    raw: list[tuple[Pod, int, int]],
    pdbs: list,
    *,
    budget: int,
    min_gain: int = 1,
) -> RebalancePlan:
    """Bound a raw diff into the executable plan (see module doc)."""
    plan = RebalancePlan(planned=len(raw))
    if not raw or budget <= 0:
        return plan
    vocab = batch.vocab
    gains: list[int] = []
    reqs: list[np.ndarray] = []
    for pod, src, dst in raw:
        req = np.asarray(
            vocab.vectorize(pod.resource_request()), dtype=np.int64
        )
        reqs.append(req)
        gains.append(
            packing_score(batch, dst)
            - packing_score(batch, src, extra_used=-req)
        )
    # least-important first, best gain first within a priority class
    # (gain BEFORE recency — start_time is near-unique, so it would
    # otherwise decide everything and budget bounding could keep a
    # gain-1 move while dropping a gain-40 one), newest-started then
    # pod key as the deterministic tiebreaks
    order = sorted(
        range(len(raw)),
        key=lambda i: (
            raw[i][0].effective_priority,
            -gains[i],
            -raw[i][0].start_time,
            raw[i][0].key,
        ),
    )
    free = (batch.allocatable - batch.used).copy()
    cnt = batch.pod_count.copy().astype(np.int64)
    selected: list[tuple[Pod, int, int, int]] = []
    for i in order:
        if len(selected) >= budget:
            break
        pod, src, dst = raw[i]
        if gains[i] < min_gain:
            continue
        req = reqs[i]
        if np.any(req > free[:, dst]):
            continue  # not executable against current truth
        if cnt[dst] + 1 > int(batch.max_pods[dst]):
            continue
        free[:, dst] -= req
        cnt[dst] += 1
        free[:, src] += req
        cnt[src] -= 1
        selected.append((pod, src, dst, gains[i]))
    violating, safe = classify_pdb_violations(
        [s[0] for s in selected], pdbs
    )
    plan.pdb_blocked = len(violating)
    safe_keys = {p.key for p in safe}
    plan.moves = [
        Move(
            pod=pod,
            source=slot_names[src],
            target=slot_names[dst],
            source_slot=src,
            target_slot=dst,
            gain=gain,
        )
        for pod, src, dst, gain in selected
        if pod.key in safe_keys
    ]
    return plan
