"""Cross-thread hardening for the serve-mode surfaces (SURVEY §6.2): the
decoupled binding cycle's three-phase locking vs concurrent ingest, and
the delete-during-bind window. The reference's analog is `go test -race`
over the binding-goroutine overlap; here the invariants are asserted
directly on the shared state after real thread interleavings."""

import threading
import time

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState


def test_concurrent_ingest_during_scheduling():
    """Writer threads create pods and delete bound pods while the
    scheduler drains; afterwards the cache, cluster, and queue must agree
    and every surviving pod must be bound exactly once to a live node."""
    cs = ClusterState()
    for i in range(8):
        cs.create_node(
            MakeNode().name(f"n{i}").capacity(
                {"cpu": "16", "memory": "64Gi", "pods": "50"}
            ).obj()
        )
    sched = Scheduler(cs, SchedulerConfig(batch_size=64))
    stop = threading.Event()
    created = []
    errors = []

    def creator(tag):
        try:
            for i in range(120):
                p = MakePod().name(f"{tag}-{i:03}").req(
                    {"cpu": "100m", "memory": "64Mi"}
                ).obj()
                cs.create_pod(p)
                created.append(p.key)
                if i % 10 == 9:
                    time.sleep(0.001)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    def deleter():
        try:
            while not stop.is_set():
                bound = [p for p in cs.list_pods() if p.node_name]
                if bound:
                    victim = bound[0]
                    try:
                        cs.delete_pod(victim.namespace, victim.name)
                    except ApiError:
                        pass
                time.sleep(0.002)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [
        threading.Thread(target=creator, args=(f"w{k}",)) for k in range(2)
    ] + [threading.Thread(target=deleter)]
    for t in threads:
        t.start()
    # drain while the writers run
    deadline = time.time() + 60
    while any(t.is_alive() for t in threads[:2]) and time.time() < deadline:
        sched.schedule_batch()
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    # settle the survivors
    for _ in range(200):
        r = sched.schedule_batch()
        if not (r.scheduled or r.unschedulable or r.bind_failures):
            break
    assert not errors, errors

    with cs.lock:
        pods = cs.list_pods()
        node_names = {n.name for n in cs.list_nodes()}
        # every bound pod points at a live node
        for p in pods:
            if p.node_name:
                assert p.node_name in node_names
        # cache agrees with cluster: per-node bound sets match
        cache_keys = {
            key
            for info in sched.cache.nodes.values()
            for key in info.pods
        }
        cluster_keys = {p.key for p in pods if p.node_name}
        assert cache_keys == cluster_keys
        # conservation: cache used cpu == sum of bound requests per node
        for info in sched.cache.nodes.values():
            want = sum(
                q.resource_request().get("cpu", 0)
                for q in info.pods.values()
            )
            assert info.used.get("cpu", 0) == want


def test_delete_during_bind_window():
    """A pod deleted while its bind is in flight (the unlocked window of
    the decoupled binding cycle) must not be requeued or resurrected, and
    the assume must be rolled back."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n0").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "10"}
        ).obj()
    )
    sched = Scheduler(cs, SchedulerConfig(batch_size=8))

    def fault(pod, node_name):
        # simulate the pod being deleted by another client exactly at the
        # binding subresource call
        cs.delete_pod(pod.namespace, pod.name)
        raise ApiError("NotFound", pod.key)

    cs.bind_fault = fault
    cs.create_pod(
        MakePod().name("ghost").req({"cpu": "1", "memory": "1Gi"}).obj()
    )
    r = sched.schedule_batch()
    assert r.scheduled == []
    cs.bind_fault = None
    # no resurrection: further batches find nothing to do
    for _ in range(3):
        r = sched.schedule_batch()
        assert not (r.scheduled or r.unschedulable or r.bind_failures)
    assert all(p.name != "ghost" for p in cs.list_pods())
    # the assume was rolled back: a full-size pod fits
    cs.create_pod(
        MakePod().name("full").req({"cpu": "8", "memory": "1Gi"}).obj()
    )
    r = sched.schedule_batch()
    assert [k for k, _ in r.scheduled] == ["default/full"]


def test_ingest_not_blocked_by_slow_wire_bind():
    """The three-phase lock: a bind stalled ON THE WIRE (extender bind
    delegate) must not hold the cluster lock — an ingest write completes
    WHILE the bind is still in flight."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n0").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "10"}
        ).obj()
    )
    sched = Scheduler(cs, SchedulerConfig(batch_size=8))
    entered = threading.Event()
    release = threading.Event()

    class StallingBinder:
        """Bind-verb-only extender client whose wire call parks until
        told — exercises the real extender-delegate path of
        _commit_binding, which runs without the cluster lock."""

        from types import SimpleNamespace

        is_binder = True
        cfg = SimpleNamespace(filter_verb="", prioritize_verb="", bind_verb="b")

        def is_interested(self, pod):
            return True

        def bind(self, pod, node_name):
            entered.set()
            assert release.wait(timeout=30), "never released"
            cs.bind(pod.namespace, pod.name, node_name)

    sched.extender_clients = [StallingBinder()]
    cs.create_pod(
        MakePod().name("slow").req({"cpu": "1", "memory": "1Gi"}).obj()
    )
    t = threading.Thread(target=sched.schedule_batch)
    t.start()
    assert entered.wait(timeout=30)
    # the wire bind is mid-flight RIGHT NOW: ingest must succeed before
    # it completes, proving the lock is not held across the wire call
    cs.create_pod(
        MakePod().name("ingested").req({"cpu": "1", "memory": "1Gi"}).obj()
    )
    assert any(p.name == "ingested" for p in cs.list_pods())
    release.set()
    t.join(timeout=30)
    assert not t.is_alive()
    assert cs.get_pod("default", "slow").node_name == "n0"
