"""kubernetes_tpu.analysis — tracer-safety & lock-discipline analyzer.

A self-contained AST static analyzer (stdlib only). Two engine tiers:

- per-module passes (PR 1): accidental host<->device syncs on the
  solve hot path (TPU001/TPU002/TPU003), lexical lock discipline
  (LOCK001), metric-name drift (MET001);
- project passes (Analyzer v2) over the cross-module symbol table and
  call graph (:mod:`.project`): lock-order deadlock detection
  (LOCK002), epoch/role fence discipline (FENCE001), retry discipline
  (RETRY001), cross-module host-sync escape (TPU004), and two-way
  metrics-doc drift (MET002).

Usage::

    python -m kubernetes_tpu.analysis [--json] [--sarif out] [paths...]
    findings = analysis.run_paths(["kubernetes_tpu/"])

Annotations and rule semantics: analysis/README.md. The in-process
pytest gate is tests/test_static_analysis.py; the suppression-debt
ratchet baseline lives in analysis/suppression_baseline.json.
"""

from __future__ import annotations

from pathlib import Path

from .core import (
    AnalysisContext,
    Finding,
    Pass,
    SourceModule,
    apply_suppressions,
    suppression_findings,
)
from .passes import ALL_PASSES, ALL_PROJECT_PASSES
from .project import ProjectGraph, ProjectPass, build_project
from .registry import default_context

__all__ = [
    "ALL_PASSES",
    "ALL_PROJECT_PASSES",
    "AnalysisContext",
    "Finding",
    "Pass",
    "ProjectGraph",
    "ProjectPass",
    "SourceModule",
    "analyze_module",
    "analyze_project",
    "analyze_source",
    "analyze_sources",
    "build_project",
    "default_context",
    "load_modules",
    "run_paths",
]

_SORT_KEY = lambda f: (f.path, f.line, f.rule, f.message)  # noqa: E731


def analyze_module(
    module: SourceModule,
    ctx: AnalysisContext | None = None,
    passes=None,
) -> list[Finding]:
    """Run the per-module pass set over one parsed module, apply
    suppressions, and enforce the reason requirement (KTPU000)."""
    ctx = ctx or default_context()
    findings: list[Finding] = []
    for cls in passes or ALL_PASSES:
        findings.extend(cls().run(module, ctx))
    apply_suppressions(module, findings)
    findings.extend(suppression_findings(module))
    findings.sort(key=_SORT_KEY)
    return findings


def analyze_source(
    source: str,
    filename: str = "snippet.py",
    ctx: AnalysisContext | None = None,
    passes=None,
) -> list[Finding]:
    """Fixture-test entry point: analyze an in-memory snippet with the
    per-module passes."""
    return analyze_module(
        SourceModule.parse(filename, source=source), ctx=ctx, passes=passes
    )


def analyze_project(
    modules,
    ctx: AnalysisContext | None = None,
    passes=None,
    project_passes=None,
) -> list[Finding]:
    """The full engine: per-module passes on each module, project
    passes once over the cross-module graph, suppressions applied to
    everything by line, one globally stable-sorted finding list."""
    ctx = ctx or default_context()
    modules = list(modules)
    per_module: dict[str, list[Finding]] = {m.path: [] for m in modules}
    stray: list[Finding] = []  # non-module paths (e.g. the metrics doc)

    for module in modules:
        for cls in passes if passes is not None else ALL_PASSES:
            per_module[module.path].extend(cls().run(module, ctx))

    project = build_project(modules, ctx)
    use = (
        project_passes if project_passes is not None else ALL_PROJECT_PASSES
    )
    for cls in use:
        for f in cls().run_project(project, ctx):
            if f.path in per_module:
                per_module[f.path].append(f)
            else:
                stray.append(f)

    findings: list[Finding] = []
    for module in modules:
        batch = per_module[module.path]
        apply_suppressions(module, batch)
        batch.extend(suppression_findings(module))
        findings.extend(batch)
    findings.extend(stray)
    findings.sort(key=_SORT_KEY)
    return findings


def analyze_sources(
    sources: dict,
    ctx: AnalysisContext | None = None,
    passes=(),
    project_passes=None,
) -> list[Finding]:
    """Fixture-test entry point for PROJECT rules: a dict of
    {filename: source} forming one in-memory project. Per-module passes
    default to OFF so project-rule fixtures stay single-purpose."""
    modules = [
        SourceModule.parse(name, source=src)
        for name, src in sorted(sources.items())
    ]
    return analyze_project(
        modules, ctx=ctx, passes=passes, project_passes=project_passes
    )


def collect_files(paths) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.is_file() and p.suffix == ".py":
            files.append(p)
        else:
            # a typo'd path silently scanning nothing would leave a CI
            # gate permanently green (review-caught) — fail loudly
            raise FileNotFoundError(
                f"{p}: not a directory or .py file — nothing to analyze"
            )
    return files


def load_modules(paths=None) -> tuple[list[SourceModule], list[Finding]]:
    """Parse the analyzed set (default: the kubernetes_tpu package this
    module ships in); unparsable files become KTPU001 findings."""
    if not paths:
        paths = [Path(__file__).resolve().parents[1]]
    modules: list[SourceModule] = []
    broken: list[Finding] = []
    for f in collect_files(paths):
        try:
            modules.append(SourceModule.parse(f))
        except SyntaxError as e:
            broken.append(
                Finding(
                    rule="KTPU001",
                    path=str(f),
                    line=e.lineno or 0,
                    message=f"syntax error: {e.msg}",
                )
            )
    return modules, broken


def run_paths(
    paths=None,
    ctx: AnalysisContext | None = None,
    passes=None,
    project_passes=None,
) -> list[Finding]:
    """Analyze files/directories (default: the kubernetes_tpu package).
    Returns ALL findings; callers filter on ``suppressed`` for gating."""
    modules, broken = load_modules(paths)
    findings = analyze_project(
        modules, ctx=ctx, passes=passes, project_passes=project_passes
    )
    findings.extend(broken)
    findings.sort(key=_SORT_KEY)
    return findings
