"""Gang membership bookkeeping (host-side, no device surface).

The tracker answers three questions the scheduler's pop gate and
commit path ask under the cluster lock:

- which gang does this pod belong to, and how many members does the
  gang need (``gang_of`` / ``min_member``);
- how long has the gang been waiting to assemble (``note_seen`` /
  ``first_seen`` — the min-member timeout that keeps a forever-short
  gang from parking its members in the queue indefinitely);
- how many consecutive solve rounds released the gang without a full
  commit (``note_incomplete`` — past ``GangConfig.quarantine_after``
  the whole gang is quarantined as a unit, exactly like a poison pod,
  so an unsatisfiable gang cannot starve the batch loop).

Everything here is guarded by the scheduler's cluster lock (the same
discipline as ``Scheduler._quarantine``): ktpu: guarded-by(cluster.lock)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api.objects import Pod

GANG_LABEL = "scheduling.x-k8s.io/pod-group"
MIN_MEMBER_ANNOTATION = "scheduling.x-k8s.io/pod-group-min-member"


class GangUnsatisfiableError(Exception):
    """Raised/recorded when a gang is quarantined as a unit: its
    membership can never assemble (min-member timeout) or its solve
    deterministically fails every round (consecutive-incomplete
    limit)."""


@dataclass(frozen=True)
class GangConfig:
    """Runtime gang-scheduling configuration (config/types.py parses
    the ``gang:`` YAML section into one of these)."""

    # seconds a gang may wait below its min-member quorum before the
    # members present are quarantined (TTL re-admit still applies, so
    # a late-arriving member can complete the gang after re-admission)
    min_member_timeout: float = 30.0
    # consecutive released (incomplete) solve rounds before the whole
    # gang quarantines as a unit
    quarantine_after: int = 3
    # heterogeneity scoring weight (score points per 1.0 of relative
    # throughput); 0 disables the fold
    throughput_weight: int = 0
    # workload-class -> {accelerator-class -> relative throughput}
    class_throughput: dict = field(default_factory=dict)


class GangTracker:
    """Per-gang assembly + failure bookkeeping."""

    def __init__(self, config: GangConfig) -> None:
        self.config = config
        # gang id -> wall-clock first seen below quorum / first popped
        self._first_seen: dict[str, float] = {}
        # gang id -> consecutive incomplete (released) rounds
        self._incomplete: dict[str, int] = {}

    @staticmethod
    def gang_of(pod: Pod) -> str | None:
        """The pod's gang id (``namespace/group``), or None."""
        name = pod.labels.get(GANG_LABEL)
        if not name:
            return None
        return f"{pod.namespace}/{name}"

    @staticmethod
    def min_member(pod: Pod) -> int:
        """The pod's declared quorum; malformed or missing annotations
        degrade to 1 (the pod schedules as a singleton gang) rather
        than wedging admission."""
        raw = pod.annotations.get(MIN_MEMBER_ANNOTATION, "")
        try:
            return max(int(raw), 1)
        except (TypeError, ValueError):
            return 1

    def note_seen(self, gang_id: str, now: float) -> float:
        """Record (and return) the gang's first-seen timestamp."""
        return self._first_seen.setdefault(gang_id, now)

    def first_seen(self, gang_id: str) -> float | None:
        return self._first_seen.get(gang_id)

    def note_incomplete(self, gang_id: str) -> int:
        """One more released round; returns the consecutive count."""
        n = self._incomplete.get(gang_id, 0) + 1
        self._incomplete[gang_id] = n
        return n

    def incomplete_rounds(self, gang_id: str) -> int:
        return self._incomplete.get(gang_id, 0)

    def note_complete(self, gang_id: str) -> float | None:
        """The gang fully committed: reset failure bookkeeping and
        return the first-seen timestamp (time-to-full-gang metric)."""
        self._incomplete.pop(gang_id, None)
        return self._first_seen.pop(gang_id, None)

    def note_quarantined(self, gang_id: str) -> None:
        """The gang quarantined as a unit: the TTL re-admit starts a
        fresh assembly window with a fresh incomplete budget (the
        per-pod quarantine backoff already grows across repeats)."""
        self._incomplete.pop(gang_id, None)
        self._first_seen.pop(gang_id, None)
