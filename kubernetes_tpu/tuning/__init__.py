"""Closed-loop hot-path auto-tuning (ISSUE 13).

The engine exports a rich measurement surface — chain fraction,
unhidden reads per batch, h2d/d2h byte counters, per-chunk solve time,
slot discards, CAS conflicts — but the knobs that govern the hot path
were static: ``drain_backlog`` chunked by the byte model alone,
``stream_depth`` was a constant, ``pipeline_split`` used a one-off
EWMA rule, and the fleet write-behind flush size was hard-coded. This
package closes the loop from the live metrics back to those knobs:

- :mod:`window` — ``CounterWindow``: a bounded host-side sampler of
  the counters the loops already tick (no new device syncs), and the
  ONE home of the RTT / per-pod-solve estimators the pipeline-split
  rule reads — so the adaptive split rule and the split controller can
  never fight over the knob from two private estimates.
- :mod:`controllers` — ``HillClimber``: bounded hill-climbing with
  hysteresis (a move must beat the incumbent by a margin), revert on
  regression, and settle detection (stop probing once neither
  direction improves). An accepted A->B move requires
  ``obj(B) > obj(A) * (1 + hysteresis)``, so an A<->B oscillation is
  impossible by construction.
- :mod:`runtime` — ``TuningRuntime``: the per-knob controllers (drain
  chunk size, ``stream_depth``, ``pipeline_split``, fleet write-behind
  flush batch) under hard guardrails: a proposed chunk shape must pass
  ``solver/budget.py``'s HBM assertion BEFORE it is ever applied,
  stream-depth changes only take effect at ring-drain boundaries, and
  every adjustment is journaled (decision, trigger counters, old->new)
  through the ``scheduler_tuning_*`` metric family and ``tuning``
  spans.
- :mod:`profile` — tuned values persist as a standard
  ``KubeSchedulerConfiguration``-shaped document (tuned config in,
  standard config out): a cluster that converged once can pin the
  result statically with ``tuning.enabled: false``.

To pin a knob statically, set its config value (e.g.
``tpuSolver.streamDepth``) and drop it from ``tuning.knobs``.
"""

from .controllers import Decision, HillClimber
from .runtime import TuningConfig, TuningRuntime
from .window import CounterWindow

__all__ = [
    "CounterWindow",
    "Decision",
    "HillClimber",
    "TuningConfig",
    "TuningRuntime",
]
