"""Replayable trace format for the cluster simulator.

A trace is a JSON-lines stream, written in strict chronological order:

- one ``{"k": "h", ...}`` header (seed, profile, cycle count, harness
  config) — enough to re-derive a fresh run;
- ``{"k": "e", "c": <cycle>, "op": ..., ...}`` churn events, exactly as
  the generators produced them (pods/nodes serialized through the api
  objects' wire shapes, so replay rebuilds identical objects);
- ``{"k": "d", "t": <tag>, "x": <value>}`` fault **decisions** — every
  point where an injector consulted randomness DURING a scheduler run
  (bind faults, watch-delivery pumps, duplications, extender verdicts,
  permit stalls). Their count depends on scheduler-internal call
  sequences, so they are journaled by consumption order instead of
  being re-derived;
- one ``{"k": "f", ...}`` footer (final bindings, violations, summary).

Replay applies the event lines literally and feeds the decision lines
back through the same injectors (``DecisionJournal`` in replay mode),
so a recorded failure reproduces bit-for-bit even if the generator code
has since changed. Determinism of a *fresh* run is separate and
stronger: same seed + profile ⇒ byte-identical trace (the CLI's
``--selfcheck`` and scripts/ci.sh verify this).

Nothing wall-clock ever enters a trace — the harness runs on
``utils.clock.FakeClock`` virtual time.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path


def canonical(obj) -> str:
    """One canonical JSON encoding so traces are byte-comparable."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class TraceWriter:
    def __init__(self) -> None:
        self.lines: list[str] = []

    def write(self, record: dict) -> None:
        self.lines.append(canonical(record))

    def header(self, **fields) -> None:
        self.write({"k": "h", "v": 1, **fields})

    def event(self, cycle: int, op: str, **fields) -> None:
        self.write({"k": "e", "c": cycle, "op": op, **fields})

    def decision(self, tag: str, value) -> None:
        self.write({"k": "d", "t": tag, "x": value})

    def footer(self, **fields) -> None:
        self.write({"k": "f", **fields})

    def digest(self) -> str:
        h = hashlib.sha256()
        for line in self.lines:
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    def dump(self, path: str | Path) -> None:
        Path(path).write_text("\n".join(self.lines) + "\n")


class TraceError(Exception):
    """A replay diverged from (or could not parse) its trace."""


class TraceReader:
    """Parsed trace: header dict, events grouped by cycle, decisions in
    consumption order, footer dict (None when the run died mid-write)."""

    def __init__(self, lines: list[str]) -> None:
        self.header: dict | None = None
        self.events_by_cycle: dict[int, list[dict]] = {}
        self.decisions: list[dict] = []
        self.footer: dict | None = None
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise TraceError(f"line {i + 1}: not JSON: {e}") from e
            kind = rec.get("k")
            if kind == "h":
                self.header = rec
            elif kind == "e":
                self.events_by_cycle.setdefault(int(rec["c"]), []).append(rec)
            elif kind == "d":
                self.decisions.append(rec)
            elif kind == "f":
                self.footer = rec
            else:
                raise TraceError(f"line {i + 1}: unknown record kind {kind!r}")
        if self.header is None:
            raise TraceError("trace has no header record")

    @classmethod
    def load(cls, path: str | Path) -> "TraceReader":
        return cls(Path(path).read_text().splitlines())
