"""Fault injectors at the scheduler's real boundaries.

Each injector sits on a seam production code already exposes — none of
them monkeypatch scheduler internals:

- ``BindFaultInjector``   → ``ClusterState.bind_fault`` (the apiserver-
  side rejection hook the binding subresource consults);
- ``DelayedWatchBus``     → interposed between ``ClusterState._emit``
  and ``Scheduler._on_event`` via subscribe/unsubscribe, modeling the
  informer relay: at-least-once delivery, arbitrary delay, duplication,
  but never reordering (client-go watch streams are ordered);
- ``FlakyExtenderTransport`` → ``HTTPExtenderClient.transport`` (the
  wire seam), so timeout/5xx verdicts travel the real ExtenderError
  paths including the non-ignorable batch abort;
- ``StallingPermitPlugin`` → a real out-of-tree PermitPlugin, parking
  pods in the WaitingPods map;
- ``SolverFaultInjector``   → ``Scheduler._solve_fault`` (the
  solver-boundary seam, called with (pods, tier) before every solve
  attempt at every fallback-ladder tier): injected device/runtime
  errors at device tiers (exercising the circuit breaker + fallback
  ladder) and poison-pod failures at EVERY tier including host
  (exercising the bisection quarantine).

Every random draw an injector makes DURING a scheduler run goes through
the :class:`DecisionJournal`, because the number and order of draws
depend on scheduler-internal call sequences. Recording them makes a
trace replay bit-for-bit even across generator/scheduler code drift;
asserting the tag on replay catches call-sequence divergence at the
first differing decision instead of at the final-bindings diff.
"""

from __future__ import annotations

import random
from typing import Callable

from .. import metrics
from ..framework.interface import PermitPlugin, Status, StatusCode
from ..state.cluster import ApiError, ClusterState, Event
from .trace import TraceError, TraceWriter


class DecisionJournal:
    """Record mode: compute the value, journal it, return it.
    Replay mode: pop the next journaled decision, assert the tag
    matches (divergence = the run is no longer following the trace),
    return the recorded value."""

    def __init__(
        self, writer: TraceWriter | None, replay: list[dict] | None = None
    ) -> None:
        self._writer = writer
        self._replay = list(replay) if replay is not None else None
        self._pos = 0

    @property
    def replaying(self) -> bool:
        return self._replay is not None

    def decide(self, tag: str, compute: Callable[[], object]):
        if self._replay is not None:
            if self._pos >= len(self._replay):
                raise TraceError(
                    f"replay exhausted its decision journal at {tag!r} "
                    f"(decision #{self._pos + 1})"
                )
            rec = self._replay[self._pos]
            self._pos += 1
            if rec["t"] != tag:
                raise TraceError(
                    f"replay diverged at decision #{self._pos}: trace has "
                    f"{rec['t']!r}, run asked for {tag!r}"
                )
            return rec["x"]
        value = compute()
        if self._writer is not None:
            self._writer.decision(tag, value)
        return value

    def leftover(self) -> int:
        """Unconsumed decisions after a replay (should be 0)."""
        return 0 if self._replay is None else len(self._replay) - self._pos


class BindFaultInjector:
    """Installed as ``cluster.bind_fault``: fails scheduler-initiated
    binds with apiserver-shaped errors. Suspended while the harness
    itself binds (external competing binds are churn, not faults)."""

    def __init__(
        self, journal: DecisionJournal, rng: random.Random, rate: float
    ) -> None:
        self._journal = journal
        self._rng = rng
        self.rate = rate
        self.suspended = False
        self.settling = False  # drain phase: stop injecting so runs settle
        self.injected = 0

    def __call__(self, pod, node_name: str) -> None:
        if self.suspended or self.settling or self.rate <= 0:
            return
        fault = self._journal.decide(
            "bind_fault", lambda: int(self._rng.random() < self.rate)
        )
        if fault:
            self.injected += 1
            metrics.sim_faults_injected_total.labels("bind_conflict").inc()
            raise ApiError(
                "Conflict", f"sim: injected bind conflict for {pod.key}"
            )


class DelayedWatchBus:
    """At-least-once, in-order watch delivery between the state service
    and ONE subscriber (the scheduler). ``ingest`` runs under the
    cluster lock (ClusterState emits synchronously); delivery happens at
    ``pump``/``pump_all``, which re-acquires the lock so the handler's
    holds(cluster.lock) contract is preserved.

    Delay policy is the caller's: the harness pumps between cycles and —
    through the scheduler's post-dispatch hook — inside the
    dispatch→apply window of in-flight solves, which is exactly where
    delayed events exercise the conflict fence. Duplication re-delivers
    an event immediately after its original (adjacent duplicate): the
    at-least-once shape informers actually produce, without reordering.
    """

    def __init__(
        self,
        cluster: ClusterState,
        deliver: Callable[[Event], None],
        journal: DecisionJournal,
        rng: random.Random,
        *,
        delaying: bool = True,
        dup_rate: float = 0.0,
    ) -> None:
        self._cluster = cluster
        self._deliver = deliver
        self._journal = journal
        self._rng = rng
        self.delaying = delaying
        self.dup_rate = dup_rate
        self.pending: list[Event] = []
        self.delivered = 0
        self.duplicated = 0

    # runs under cluster.lock (ClusterState._emit fires synchronously)
    def ingest(self, ev: Event) -> None:
        if not self.delaying:
            self._deliver_one(ev)
            return
        metrics.sim_faults_injected_total.labels("watch_delay").inc()
        self.pending.append(ev)

    def _deliver_one(self, ev: Event) -> None:
        self._deliver(ev)
        self.delivered += 1
        if self.dup_rate > 0:
            dup = self._journal.decide(
                "watch_dup", lambda: int(self._rng.random() < self.dup_rate)
            )
            if dup:
                self.duplicated += 1
                metrics.sim_faults_injected_total.labels(
                    "watch_duplicate"
                ).inc()
                self._deliver(ev)

    def pump(self, n: int) -> int:
        """Deliver the next ``n`` pending events (in order), under the
        cluster lock. Returns how many were delivered."""
        if n <= 0 or not self.pending:
            return 0
        batch, self.pending = self.pending[:n], self.pending[n:]
        with self._cluster.lock:
            for ev in batch:
                self._deliver_one(ev)
        return len(batch)

    def pump_all(self) -> int:
        return self.pump(len(self.pending))

    def pending_pod_adds(self) -> set[str]:
        """Keys of pods whose ADDED event has not been delivered yet —
        the lost-pod invariant must not count them against the
        scheduler (it cannot know about them)."""
        return {
            ev.obj.key
            for ev in self.pending
            if ev.kind == "Pod" and ev.type == "ADDED"
        }


class FlakyExtenderTransport:
    """Injectable wire for ``HTTPExtenderClient``: answers filter/
    prioritize with pass-all verdicts, or fails the call (timeout / 5xx)
    per journaled decision. Failures raise OSError — the transport
    contract — which the client maps onto ExtenderError exactly like a
    real connection error."""

    def __init__(
        self, journal: DecisionJournal, rng: random.Random, rate: float
    ) -> None:
        self._journal = journal
        self._rng = rng
        self.rate = rate
        self.settling = False
        self.calls = 0
        self.failed = 0

    def __call__(self, verb: str, payload: dict):
        self.calls += 1
        mode = "ok"
        if not self.settling and self.rate > 0:
            def draw():
                if self._rng.random() >= self.rate:
                    return "ok"
                return self._rng.choice(["timeout", "http500"])

            mode = self._journal.decide("extender_fault", draw)
        if mode == "timeout":
            self.failed += 1
            metrics.sim_faults_injected_total.labels("extender_timeout").inc()
            raise OSError("sim: injected extender timeout")
        if mode == "http500":
            self.failed += 1
            metrics.sim_faults_injected_total.labels("extender_5xx").inc()
            raise OSError("sim: injected HTTP 500")
        if "filter" in verb:
            if payload.get("nodenames") is not None:
                names = list(payload["nodenames"])
            else:
                names = [
                    d.get("metadata", {}).get("name")
                    for d in (payload.get("nodes") or {}).get("items") or []
                ]
            return {"nodenames": names}
        return []  # prioritize: empty HostPriorityList (no opinion)


class SolverFaultInjector:
    """Installed as ``Scheduler._solve_fault``: raises
    ``SolverFaultError`` from inside the dispatch path, the one real
    boundary the sim couldn't previously reach (every other injector
    sits above ``schedule_batch``).

    Two failure modes:

    - **device faults** (``rate`` within the optional virtual-clock
      ``window``): raised at every tier EXCEPT the pure-host rung —
      a real accelerator outage cannot take down host python, and the
      exemption is what makes "the ladder always has a working floor"
      testable. Draws are journaled (replay-stable).
    - **poison pods**: any batch containing a POISON_LABEL-marked pod
      fails at EVERY tier including host (data that breaks
      tensorize/solve), deterministically — no RNG, no journal entry —
      which is exactly the shape the bisection quarantine isolates.
    """

    def __init__(
        self,
        journal: DecisionJournal,
        rng: random.Random,
        clock,
        *,
        rate: float = 0.0,
        window: tuple = (),
    ) -> None:
        self._journal = journal
        self._rng = rng
        self._clock = clock
        self.rate = rate
        self.window = tuple(window)
        self.settling = False
        self.injected = 0
        self.poison_hits = 0

    def __call__(self, pods, tier: str) -> None:
        from ..resilience import TIER_HOST, SolverFaultError
        from .generators import POISON_LABEL

        poison = sorted(
            p.key for p in pods if p.labels.get(POISON_LABEL)
        )
        if poison:
            self.poison_hits += 1
            metrics.sim_faults_injected_total.labels("poison_pod").inc()
            raise SolverFaultError(
                f"sim: poison pod(s) {', '.join(poison)} break the "
                f"solve (tier {tier})"
            )
        if tier == TIER_HOST or self.settling or self.rate <= 0:
            return
        if self.window:
            now = self._clock.now()
            if not (self.window[0] <= now < self.window[1]):
                return
        fault = self._journal.decide(
            "solver_fault", lambda: int(self._rng.random() < self.rate)
        )
        if fault:
            self.injected += 1
            metrics.sim_faults_injected_total.labels("solver_fault").inc()
            raise SolverFaultError(
                f"sim: injected device solve failure (tier {tier})"
            )


class SimulatedCrash(Exception):
    """The scheduler process died (kill -9, OOM, GC-stall eviction).
    Raised from the ``_pre_commit_hook`` seam — after a batch's pods
    are assumed and approved, before any bind commits — and caught by
    the HARNESS, never the scheduler: from the cluster's point of view
    the process simply stopped, with every piece of incarnation-local
    state (assumed pods, Permit waiters, in-flight maps, deferred
    solves) evaporating. The harness then constructs a fresh
    incarnation on the same ClusterState (sim/harness.py)."""


class CrashInjector:
    """Installed as ``Scheduler._pre_commit_hook``: once armed, the
    next batch that reaches its commit point dies mid-batch — the
    deterministic kill-after-assume-before-bind the crash_restart
    profile drives. One-shot: the raise disarms it (the restarted
    incarnation keeps running)."""

    def __init__(self) -> None:
        self.armed = False
        self.crashes = 0

    def arm(self) -> None:
        self.armed = True

    def __call__(self, pending) -> None:
        if not self.armed:
            return
        self.armed = False
        self.crashes += 1
        metrics.sim_faults_injected_total.labels("crash").inc()
        raise SimulatedCrash(
            f"sim: process crash mid-batch ({len(pending)} pod(s) "
            "assumed+approved, none committed)"
        )


class StallingPermitPlugin(PermitPlugin):
    """Out-of-tree Permit plugin: WAITs a pod's FIRST attempt with some
    probability; retries (and everything in settling mode) pass. Parked
    pods are later allowed by the harness or expire on the virtual
    clock — both verdict paths of the WaitingPods map."""

    def __init__(
        self,
        journal: DecisionJournal,
        rng: random.Random,
        rate: float,
        timeout: float,
    ) -> None:
        self._journal = journal
        self._rng = rng
        self.rate = rate
        self.timeout = timeout
        self.settling = False
        self._stalled_once: set[str] = set()
        self.stalls = 0

    def name(self) -> str:
        return "SimStallingPermit"

    def permit(self, state, pod, node_name: str):
        if (
            self.settling
            or self.rate <= 0
            or pod.key in self._stalled_once
        ):
            return Status.success(), 0.0
        stall = self._journal.decide(
            "permit_stall", lambda: int(self._rng.random() < self.rate)
        )
        if stall:
            self._stalled_once.add(pod.key)
            self.stalls += 1
            metrics.sim_faults_injected_total.labels("permit_stall").inc()
            return Status(StatusCode.WAIT), self.timeout
        return Status.success(), 0.0
